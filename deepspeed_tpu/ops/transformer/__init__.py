"""Namespace parity with the reference's ``deepspeed/ops/transformer``
kernel package — on TPU the fused transformer building blocks are the
Pallas kernels plus the fused cross-entropy; XLA fuses the rest of the
block body, so there is no monolithic "DeepSpeedTransformerLayer" here.
"""

from ..pallas import (bias_gelu, flash_attention, fused_softmax, gelu,
                      layer_norm, masked_softmax)
from ..pallas.decode_attention import decode_attention

__all__ = ["flash_attention", "decode_attention", "layer_norm",
           "fused_softmax", "masked_softmax", "bias_gelu", "gelu"]
