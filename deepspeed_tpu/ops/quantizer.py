"""Grouped symmetric int8 quantization for inference weights.

Reference analogue: ``csrc/quantization/quantizer.cu`` (``ds_quantize_*``,
grouped symmetric/asymmetric with optional stochastic rounding) and the
``WeightQuantization`` checkpoint path (``runtime/weight_quantizer.py:5``).
Dequantization is meant to be traced *inside* the consuming jit so XLA
fuses the scale-multiply into the next matmul; group-wise scales keep
accuracy (MoQ-style) while weights sit in HBM at 1/4 the fp32 size.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def quantize(x: jnp.ndarray, num_groups: int = 1
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-group int8 quantization over the flattened tensor.
    Returns (q int8 [same shape], scales f32 [num_groups])."""
    flat = x.reshape(num_groups, -1).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale[:, 0]


def dequantize(q: jnp.ndarray, scales: jnp.ndarray,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    num_groups = scales.shape[0]
    flat = q.reshape(num_groups, -1).astype(jnp.float32)
    return (flat * scales[:, None]).astype(dtype).reshape(q.shape)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and "q8" in x and "scale" in x


def quantize_tree(params) -> Any:
    """Quantize every floating >=2-D leaf of a param tree to
    ``{"q8": int8 [out, ...in], "scale": f32 [out]}`` (one scale group per
    output column — matmul-friendly); biases/norms stay as-is (reference
    WeightQuantization quantizes only the GEMM weights)."""
    def q(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim >= 2 and jnp.issubdtype(leaf.dtype, jnp.floating):
            moved = jnp.moveaxis(leaf, -1, 0)        # (out, ...)
            g = moved.shape[0]
            vals, scales = quantize(moved.reshape(g, -1), num_groups=g)
            return {"q8": vals.reshape(moved.shape), "scale": scales}
        return leaf

    return jax.tree.map(q, params)


def dequantize_tree(qtree, dtype=jnp.bfloat16):
    """Inverse of quantize_tree. Safe to call inside jit — layout is
    recovered from the (static) array shapes, so XLA fuses the dequant
    into the consuming matmul."""
    def dq(leaf):
        if _is_qleaf(leaf):
            q8 = leaf["q8"]
            g = q8.shape[0]
            flat = dequantize(q8.reshape(g, -1), leaf["scale"], dtype)
            return jnp.moveaxis(flat.reshape(q8.shape), 0, -1)
        return leaf

    return jax.tree.map(dq, qtree, is_leaf=_is_qleaf)
