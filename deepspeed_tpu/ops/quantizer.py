"""Grouped symmetric int8 quantization for inference weights.

Reference analogue: ``csrc/quantization/quantizer.cu`` (``ds_quantize_*``,
grouped symmetric/asymmetric with optional stochastic rounding) and the
``WeightQuantization`` checkpoint path (``runtime/weight_quantizer.py:5``).
Dequantization is meant to be traced *inside* the consuming jit so XLA
fuses the scale-multiply into the next matmul; group-wise scales keep
accuracy (MoQ-style) while weights sit in HBM at 1/4 the fp32 size.
"""

from __future__ import annotations

import re
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

# embedding tables are excluded from weight quantization (reference
# WeightQuantization skips them; int8 embeddings measurably hurt quality)
_EMBED_PAT = re.compile(r"\b(wte|wpe|wtt|embed|embedding)\b")


def quantize(x: jnp.ndarray, num_groups: int = 1
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-group int8 quantization over the flattened tensor.
    Returns (q int8 [same shape], scales f32 [num_groups])."""
    flat = x.reshape(num_groups, -1).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale[:, 0]


def dequantize(q: jnp.ndarray, scales: jnp.ndarray,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    num_groups = scales.shape[0]
    flat = q.reshape(num_groups, -1).astype(jnp.float32)
    return (flat * scales[:, None]).astype(dtype).reshape(q.shape)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and "q8" in x and "scale" in x


def quantize_tree(params) -> Any:
    """Quantize GEMM weights of a param tree to ``{"q8": int8 [out, ...in],
    "scale": f32 [out]}`` (one scale group per output column —
    matmul-friendly). Biases/norms stay as-is, and so do embedding tables
    — the predicate is path-based, not rank-based (reference
    WeightQuantization quantizes only the GEMM weights and skips
    embeddings)."""
    def q(path, leaf):
        leaf = jnp.asarray(leaf)
        key = jax.tree_util.keystr(path)
        last = (getattr(path[-1], "key", None) or
                getattr(path[-1], "name", "")) if path else ""
        is_gemm = last in ("kernel", "w", "weight")
        if is_gemm and leaf.ndim >= 2 \
                and jnp.issubdtype(leaf.dtype, jnp.floating) \
                and not _EMBED_PAT.search(key):
            moved = jnp.moveaxis(leaf, -1, 0)        # (out, ...)
            g = moved.shape[0]
            vals, scales = quantize(moved.reshape(g, -1), num_groups=g)
            return {"q8": vals.reshape(moved.shape), "scale": scales}
        return leaf

    return jax.tree_util.tree_map_with_path(q, params)


def quantize_shardings(qtree, fp_shardings, mesh) -> Any:
    """Shardings for a quantized tree so int8 weights rest TP-sharded: the
    q8 leaf takes the fp leaf's spec with the moved-axis permutation (last
    axis became axis 0), the per-output-column scales take the output-dim
    entry of that spec."""
    def sh(qleaf, fp_sh):
        if not _is_qleaf(qleaf):
            return fp_sh
        spec = list(fp_sh.spec) if isinstance(fp_sh, NamedSharding) else []
        nd = qleaf["q8"].ndim
        spec = spec + [None] * (nd - len(spec))
        moved = [spec[-1]] + spec[:-1]               # moveaxis(-1, 0)
        return {
            "q8": NamedSharding(mesh, P(*moved)),
            "scale": NamedSharding(mesh, P(moved[0])),
        }

    return jax.tree.map(sh, qtree, fp_shardings, is_leaf=_is_qleaf)


def dequantize_tree(qtree, dtype=jnp.bfloat16):
    """Inverse of quantize_tree. Safe to call inside jit — layout is
    recovered from the (static) array shapes, so XLA fuses the dequant
    into the consuming matmul."""
    def dq(leaf):
        if _is_qleaf(leaf):
            q8 = leaf["q8"]
            g = q8.shape[0]
            flat = dequantize(q8.reshape(g, -1), leaf["scale"], dtype)
            return jnp.moveaxis(flat.reshape(q8.shape), 0, -1)
        return leaf

    return jax.tree.map(dq, qtree, is_leaf=_is_qleaf)
