"""Grouped symmetric int8 quantization for inference weights.

Reference analogue: ``csrc/quantization/quantizer.cu`` (``ds_quantize_*``,
grouped symmetric/asymmetric with optional stochastic rounding) and the
``WeightQuantization`` checkpoint path (``runtime/weight_quantizer.py:5``).
Dequantization is meant to be traced *inside* the consuming jit so XLA
fuses the scale-multiply into the next matmul; group-wise scales keep
accuracy (MoQ-style) while weights sit in HBM at 1/4 the fp32 size.
"""

from __future__ import annotations

import re
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

# embedding tables are excluded from weight quantization (reference
# WeightQuantization skips them; int8 embeddings measurably hurt quality)
_EMBED_PAT = re.compile(r"\b(wte|wpe|wtt|embed|embedding)\b")


def quantize(x: jnp.ndarray, num_groups: int = 1
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-group int8 quantization over the flattened tensor.
    Returns (q int8 [same shape], scales f32 [num_groups])."""
    flat = x.reshape(num_groups, -1).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale[:, 0]


def dequantize(q: jnp.ndarray, scales: jnp.ndarray,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    num_groups = scales.shape[0]
    flat = q.reshape(num_groups, -1).astype(jnp.float32)
    return (flat * scales[:, None]).astype(dtype).reshape(q.shape)


def _asym_range(flat: jnp.ndarray, bits: int):
    """Per-group (min, scale) of the reference's min/max-range scheme
    (quantizer.cu:565: scale=(max-min+1e-5)/2^bits) — the single home of
    that formula for both the int8-at-rest path and ds_quantize."""
    mn = jnp.min(flat, axis=1, keepdims=True)
    mx = jnp.max(flat, axis=1, keepdims=True)
    return mn, ((mx - mn) + 1e-5) / float(1 << bits)


def quantize_asym(x: jnp.ndarray, num_groups: int = 1
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Asymmetric per-group int8: per-group min/max range (reference
    ``ds_quantize_asym``, csrc/quantization/quantizer.cu:565 —
    scale=(max-min)/2^bits, values rebased to the group minimum). Returns
    (q int8, scales f32 [G], mins f32 [G]); dequant is q*scale + min
    with q rebased to [0, 255] via +128."""
    flat = x.reshape(num_groups, -1).astype(jnp.float32)
    mn, scale = _asym_range(flat, 8)
    q = jnp.clip(jnp.round((flat - mn) / scale), 0, 255) - 128
    return (q.astype(jnp.int8).reshape(x.shape), scale[:, 0], mn[:, 0])


def dequantize_asym(q: jnp.ndarray, scales: jnp.ndarray, mins: jnp.ndarray,
                    dtype=jnp.bfloat16) -> jnp.ndarray:
    num_groups = scales.shape[0]
    flat = (q.reshape(num_groups, -1).astype(jnp.float32) + 128.0)
    return (flat * scales[:, None] + mins[:, None]).astype(dtype).reshape(
        q.shape)


def ds_quantize(vals: jnp.ndarray, groups: int, bits: int = 8,
                asymmetric: bool = False, stochastic: bool = False,
                key=None) -> jnp.ndarray:
    """Fake quantization (quantize -> dequantize, original dtype/shape) with
    the reference kernel family's exact semantics
    (csrc/quantization/pt_binding.cpp:64-74 ``ds_quantize`` /
    ``ds_sr_quantize`` / ``ds_quantize_asym`` / ``ds_sr_quantize_asym``;
    kernels in quantizer.cu):

      sym       : q_scale = 2^bits / (2*absmax + 1e-5); round(v*q_scale),
                  dequant /q_scale                       (quantizer.cu:64)
      sym + sr  : truncate toward zero, bump by sign(v) with probability
                  |fractional error|, clamped inside (low_q, high_q)
                  (quantizer.cu:405-450)
      asym      : q_scale = (max-min+1e-5)/2^bits; round((v-min)/q_scale),
                  dequant *q_scale + min                 (quantizer.cu:565)
      asym + sr : floor instead of round, +1 with probability equal to the
                  fractional remainder

    ``stochastic=True`` requires a ``key`` (jax PRNG); traced and jit-safe,
    usable both for MoQ-style quantize-aware training and for low-precision
    stochastic-rounded training steps (the reference's
    StochasticTransformerBuilder training mode analogue,
    csrc/transformer/ds_transformer_cuda.cpp:1031-1046)."""
    if stochastic and key is None:
        raise ValueError("stochastic=True needs a jax PRNG `key`")
    flat = vals.reshape(groups, -1).astype(jnp.float32)
    if asymmetric:
        mn, scale = _asym_range(flat, bits)
        t = (flat - mn) / scale
        if stochastic:
            low = jnp.floor(t)
            r = jax.random.uniform(key, flat.shape)
            q = low + (r < (t - low)).astype(jnp.float32)
        else:
            q = jnp.round(t)
        # saturating clamp to the code range: at the group max t == 2^bits
        # exactly (and the stochastic +1 bump can land there too), one
        # code past the top — the int8 store would wrap it to the bottom
        q = jnp.clip(q, 0.0, float((1 << bits) - 1))
        out = q * scale + mn
    else:
        absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
        q_scale = float(1 << bits) / (2.0 * absmax + 1e-5)
        t = flat * q_scale
        high_q = float((1 << (bits - 1)) - 1)
        low_q = float(-(1 << (bits - 1)))
        if stochastic:
            ti = jnp.trunc(t)
            err = jnp.abs(t - ti)
            r = jax.random.uniform(key, flat.shape)
            bump = ((r < err) & (ti > low_q) & (ti < high_q)
                    ).astype(jnp.float32)
            q = ti + jnp.sign(t) * bump
        else:
            # saturating clamp: at v == absmax, t is a hair under
            # 2^(bits-1) and round() lands ON it — one code past high_q,
            # which an int8 store would wrap to the bottom of the range
            q = jnp.clip(jnp.round(t), low_q, high_q)
        out = q / q_scale
    return out.reshape(vals.shape).astype(vals.dtype)


def quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 KV-cache quantization, one scale group per token
    vector (the last axis — a single position's concatenated heads, the
    granularity at which cache rows are written and gathered). Same
    saturating semantics as ``ds_quantize``'s symmetric branch:
    q_scale = 2^8 / (2*absmax + 1e-5), round, clamp to [-128, 127] so the
    group extreme doesn't wrap. Returns ``(q int8 [..., D],
    scale f32 [..., 1])`` where ``scale`` is the DEQUANT multiplier —
    stored next to the int8 payload so reads are ``q * scale`` with no
    division on the hot path."""
    flat = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(flat), axis=-1, keepdims=True)
    q_scale = 256.0 / (2.0 * absmax + 1e-5)
    q = jnp.clip(jnp.round(flat * q_scale), -128.0, 127.0).astype(jnp.int8)
    return q, (1.0 / q_scale).astype(jnp.float32)


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray,
                  dtype=jnp.bfloat16) -> jnp.ndarray:
    """Inverse of ``quantize_kv``; traced inside the consuming attention
    jit so XLA fuses the broadcast-multiply into the QK/PV contractions."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def stochastic_round_bf16(x: jnp.ndarray, key) -> jnp.ndarray:
    """fp32 -> bf16 with STOCHASTIC rounding: add a uniform 16-bit value
    below the truncation point, then truncate the mantissa — unbiased in
    expectation, so repeated master->compute casts don't accumulate a
    rounding drift. This is the training-mode rounding the reference's
    StochasticTransformerBuilder kernels apply when writing fp16 outputs
    from fp32 accumulators (csrc/transformer/ds_transformer_cuda.cpp:
    1031-1046); here it is a traced cast usable on any fp32 tree (the
    engine's bf16.stochastic_rounding knob routes the per-step
    master->bf16 param cast through it). Non-finite values pass through
    the deterministic cast (bit-noise on inf lands in NaN space)."""
    x32 = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    sr = jax.lax.bitcast_convert_type(
        (bits + noise) & jnp.uint32(0xFFFF0000), jnp.float32)
    out = jnp.where(jnp.isfinite(x32), sr, x32)
    return out.astype(jnp.bfloat16)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and "q8" in x and "scale" in x


def quantize_tree(params, mode: str = "symmetric") -> Any:
    """Quantize GEMM weights of a param tree to ``{"q8": int8 [out, ...in],
    "scale": f32 [out]}`` (one scale group per output column —
    matmul-friendly); ``mode="asymmetric"`` adds a per-column ``"zmin"``
    (min/max range quantization, reference ``ds_quantize_asym``).
    Biases/norms stay as-is, and so do embedding tables — the predicate is
    path-based, not rank-based (reference WeightQuantization quantizes
    only the GEMM weights and skips embeddings)."""
    if mode not in ("symmetric", "asymmetric"):
        raise ValueError(f"quantize mode {mode!r}: use 'symmetric' or "
                         f"'asymmetric'")

    def q(path, leaf):
        leaf = jnp.asarray(leaf)
        key = jax.tree_util.keystr(path)
        last = (getattr(path[-1], "key", None) or
                getattr(path[-1], "name", "")) if path else ""
        is_gemm = last in ("kernel", "w", "weight")
        if is_gemm and leaf.ndim >= 2 \
                and jnp.issubdtype(leaf.dtype, jnp.floating) \
                and not _EMBED_PAT.search(key):
            moved = jnp.moveaxis(leaf, -1, 0)        # (out, ...)
            g = moved.shape[0]
            if mode == "asymmetric":
                vals, scales, mins = quantize_asym(moved.reshape(g, -1),
                                                   num_groups=g)
                return {"q8": vals.reshape(moved.shape), "scale": scales,
                        "zmin": mins}
            vals, scales = quantize(moved.reshape(g, -1), num_groups=g)
            return {"q8": vals.reshape(moved.shape), "scale": scales}
        return leaf

    return jax.tree_util.tree_map_with_path(q, params)


def quantize_shardings(qtree, fp_shardings, mesh) -> Any:
    """Shardings for a quantized tree so int8 weights rest TP-sharded: the
    q8 leaf takes the fp leaf's spec with the moved-axis permutation (last
    axis became axis 0), the per-output-column scales take the output-dim
    entry of that spec."""
    def sh(qleaf, fp_sh):
        if not _is_qleaf(qleaf):
            return fp_sh
        spec = list(fp_sh.spec) if isinstance(fp_sh, NamedSharding) else []
        nd = qleaf["q8"].ndim
        spec = spec + [None] * (nd - len(spec))
        moved = [spec[-1]] + spec[:-1]               # moveaxis(-1, 0)
        out = {
            "q8": NamedSharding(mesh, P(*moved)),
            "scale": NamedSharding(mesh, P(moved[0])),
        }
        if "zmin" in qleaf:
            out["zmin"] = NamedSharding(mesh, P(moved[0]))
        return out

    return jax.tree.map(sh, qtree, fp_shardings, is_leaf=_is_qleaf)


def dequantize_tree(qtree, dtype=jnp.bfloat16):
    """Inverse of quantize_tree. Safe to call inside jit — layout is
    recovered from the (static) array shapes, so XLA fuses the dequant
    into the consuming matmul."""
    def dq(leaf):
        if _is_qleaf(leaf):
            q8 = leaf["q8"]
            g = q8.shape[0]
            if "zmin" in leaf:
                flat = dequantize_asym(q8.reshape(g, -1), leaf["scale"],
                                       leaf["zmin"], dtype)
            else:
                flat = dequantize(q8.reshape(g, -1), leaf["scale"], dtype)
            return jnp.moveaxis(flat.reshape(q8.shape), 0, -1)
        return leaf

    return jax.tree.map(dq, qtree, is_leaf=_is_qleaf)
