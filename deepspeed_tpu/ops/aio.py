"""Async file I/O handle for NVMe offload (ZeRO-Infinity tier).

Reference analogue: the ``aio_handle`` exposed from
``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` (block_size / queue_depth
knobs, async pread/pwrite + wait, sync variants) consumed by the
swap_tensor swappers. Python fallback uses plain file I/O when the native
build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .op_builder import get_native_lib

# O_DIRECT granularity: 4096 covers every modern NVMe/filesystem (logical
# block 512 or 4096). Buffers, lengths and offsets must all be multiples.
DIRECT_ALIGN = 4096


def padded_nbytes(nbytes: int) -> int:
    """Round a transfer length up to the O_DIRECT granularity."""
    return -(-int(nbytes) // DIRECT_ALIGN) * DIRECT_ALIGN


def aligned_empty(n: int, dtype=np.float32) -> np.ndarray:
    """Uninitialized 1-D array holding AT LEAST ``n`` elements: the data
    pointer is DIRECT_ALIGN-aligned and the returned length is rounded up
    to the alignment boundary, so ``arr[:k]`` slices serve compute while
    ``arr[:padded_count]`` slices serve direct I/O without leaving the
    allocation. (The reference pins + aligns its aio buffers the same way,
    csrc/aio/common/deepspeed_aio_utils.cpp.)"""
    itemsize = np.dtype(dtype).itemsize
    padded = padded_nbytes(n * itemsize)
    assert padded % itemsize == 0
    raw = np.empty(padded + DIRECT_ALIGN, np.uint8)
    off = (-raw.ctypes.data) % DIRECT_ALIGN
    view = raw[off:off + padded].view(dtype)
    assert view.ctypes.data % DIRECT_ALIGN == 0
    return view


def _check_direct(array: np.ndarray, nbytes: int, offset: int) -> None:
    """ValueError (not assert: ``python -O`` must not disable this) when a
    direct-I/O request isn't fully DIRECT_ALIGN-aligned."""
    if (array.ctypes.data % DIRECT_ALIGN != 0
            or nbytes % DIRECT_ALIGN != 0
            or offset % DIRECT_ALIGN != 0):
        raise ValueError(
            f"direct I/O requires DIRECT_ALIGN({DIRECT_ALIGN})-aligned "
            f"buffer/len/offset; got data%align="
            f"{array.ctypes.data % DIRECT_ALIGN}, "
            f"len%align={nbytes % DIRECT_ALIGN}, "
            f"off%align={offset % DIRECT_ALIGN}")


_warned_direct_fallback = False


def _warn_direct_fallback() -> None:
    """``direct=True`` without the native engine degrades to buffered
    Python I/O — exactly the page-cache behavior O_DIRECT exists to avoid.
    Warn once, loudly, instead of silently re-enabling it."""
    global _warned_direct_fallback
    if not _warned_direct_fallback:
        _warned_direct_fallback = True
        import warnings
        warnings.warn(
            "AsyncIOHandle: direct=True requested but the native aio "
            "engine is unavailable; falling back to BUFFERED I/O (page "
            "cache will absorb all swap traffic). Build csrc/aio.cpp for "
            "O_DIRECT behavior.", RuntimeWarning, stacklevel=3)


class AsyncIOHandle:
    """Thread-pooled async file reader/writer over the native engine.

    Usage (mirrors reference swap_tensor usage):
        h = AsyncIOHandle(block_size=1 << 20, queue_depth=8)
        h.async_pwrite(array, path); ...; h.wait()
        h.async_pread(array, path); ...; h.wait()
    """

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 single_submit: bool = False, overlap_events: bool = True,
                 num_threads: int = 0):
        self.block_size = block_size
        self.queue_depth = queue_depth
        self._lib = get_native_lib()
        self._handle = None
        self._fds = []          # fds held until wait()
        self._pending_py = []   # python-fallback deferred ops
        if self._lib is not None:
            self._handle = self._lib.aio_handle_new(
                block_size, queue_depth, num_threads or queue_depth)

    @property
    def native(self) -> bool:
        return self._handle is not None

    # ------------------------------------------------------------- async
    def async_pwrite(self, array: np.ndarray, path: str, offset: int = 0,
                     direct: bool = False):
        """``direct=True`` bypasses the page cache (O_DIRECT; the reference
        aio engine always runs this way): the caller must pass an
        ``aligned_empty`` buffer sliced to a ``padded_nbytes`` length and an
        aligned offset — enforced with ValueError, because silent fallback would re-enable
        cache pollution at Infinity scale without anyone noticing."""
        array = np.ascontiguousarray(array)
        if self._handle is not None:
            if direct:
                _check_direct(array, array.nbytes, offset)
            fd = self._lib.aio_open(path.encode(), 1, 1 if direct else 0)
            if fd < 0:
                raise OSError(f"aio_open failed for {path}")
            self._fds.append(fd)
            self._lib.aio_pwrite(self._handle, fd,
                                 array.ctypes.data_as(ctypes.c_void_p),
                                 array.nbytes, offset)
            self._keepalive = getattr(self, "_keepalive", [])
            self._keepalive.append(array)
        else:
            if direct:
                _warn_direct_fallback()
            self._pending_py.append(("w", array, path, offset))
        return 1

    def async_pread(self, array: np.ndarray, path: str, offset: int = 0,
                    direct: bool = False):
        assert array.flags["C_CONTIGUOUS"]
        if self._handle is not None:
            if direct:
                _check_direct(array, array.nbytes, offset)
            fd = self._lib.aio_open(path.encode(), 0, 1 if direct else 0)
            if fd < 0:
                raise OSError(f"aio_open failed for {path}")
            self._fds.append(fd)
            self._lib.aio_pread(self._handle, fd,
                                array.ctypes.data_as(ctypes.c_void_p),
                                array.nbytes, offset)
        else:
            if direct:
                _warn_direct_fallback()
            self._pending_py.append(("r", array, path, offset))
        return 1

    def wait(self) -> int:
        if self._handle is not None:
            rc = self._lib.aio_wait(self._handle)
            for fd in self._fds:
                self._lib.aio_close(fd)
            self._fds.clear()
            self._keepalive = []
            if rc < 0:
                raise OSError(f"aio_wait reported {-rc} failed chunks")
            return 0
        for op, array, path, offset in self._pending_py:
            if op == "w":
                self.sync_pwrite(array, path, offset)
            else:
                self.sync_pread(array, path, offset)
        n = len(self._pending_py)
        self._pending_py.clear()
        return 0

    # -------------------------------------------------------------- sync
    def sync_pwrite(self, array: np.ndarray, path: str, offset: int = 0,
                    direct: bool = False):
        array = np.ascontiguousarray(array)
        if self._lib is not None:
            if direct:
                _check_direct(array, array.nbytes, offset)
            fd = self._lib.aio_open(path.encode(), 1, 1 if direct else 0)
            try:
                rc = self._lib.aio_sync_pwrite(
                    fd, array.ctypes.data_as(ctypes.c_void_p),
                    array.nbytes, offset)
            finally:
                self._lib.aio_close(fd)
            if rc != array.nbytes:
                raise OSError(f"short write to {path}: {rc}")
            return rc
        if direct:
            _warn_direct_fallback()
        with open(path, "r+b" if os.path.exists(path) else "wb") as f:
            f.seek(offset)
            f.write(array.tobytes())
        return array.nbytes

    def sync_pread(self, array: np.ndarray, path: str, offset: int = 0,
                   direct: bool = False):
        assert array.flags["C_CONTIGUOUS"]
        if self._lib is not None:
            if direct:
                _check_direct(array, array.nbytes, offset)
            fd = self._lib.aio_open(path.encode(), 0, 1 if direct else 0)
            try:
                rc = self._lib.aio_sync_pread(
                    fd, array.ctypes.data_as(ctypes.c_void_p),
                    array.nbytes, offset)
            finally:
                self._lib.aio_close(fd)
            if rc != array.nbytes:
                raise OSError(f"short read from {path}: {rc}")
            return rc
        if direct:
            _warn_direct_fallback()
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(array.nbytes)
        if len(data) != array.nbytes:
            # match the native path: truncated swap files must fail loudly,
            # not leave stale bytes in the destination tail
            raise OSError(f"short read from {path}: {len(data)} of "
                          f"{array.nbytes} bytes")
        array.view(np.uint8)[:] = np.frombuffer(data, np.uint8)
        return len(data)

    def __del__(self):
        try:
            if self._handle is not None and self._lib is not None:
                self._lib.aio_handle_free(self._handle)
                self._handle = None
        except Exception:
            pass
