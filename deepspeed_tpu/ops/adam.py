"""Fused Adam/AdamW as an optax-style transformation.

Reference analogues: ``csrc/adam/multi_tensor_adam.cu`` + ``ops/adam/fused_adam.py``
(GPU fused multi-tensor Adam) and ``ops/adam/cpu_adam.py`` (host SIMD Adam).
On TPU the "fusion" is XLA's: one jitted update over the whole pytree compiles
to fused elementwise kernels per shard, already multi-tensor by construction.
The implementation is written out (not delegated to optax.adam) so we control
state dtypes and sharding: ``mu``/``nu`` inherit each param's sharding, which
is what makes ZeRO-1/2/3 optimizer-state partitioning fall out of the mesh.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class AdamState(NamedTuple):
    count: jnp.ndarray
    mu: any
    nu: any


def fused_adam(learning_rate=1e-3,
               betas=(0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               state_dtype=jnp.float32) -> optax.GradientTransformation:
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=state_dtype)
        return AdamState(count=jnp.zeros((), jnp.int32),
                         mu=jax.tree.map(zeros, params),
                         nu=jax.tree.map(zeros, params))

    def update(grads, state, params=None):
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(m.dtype),
                          state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(v.dtype)),
                          state.nu, grads)
        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = jnp.ones((), jnp.float32)

        def upd(m, v, p):
            mhat = m / c1
            vhat = v / c2
            step = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay:
                if adam_w_mode:
                    step = step + weight_decay * p.astype(step.dtype)
                else:
                    # classic L2: folded into gradient => into mu; approximate
                    # by adding decay term directly (matches fused kernel mode 0)
                    step = step + weight_decay * p.astype(step.dtype)
            return (-lr * step).astype(p.dtype)

        updates = jax.tree.map(upd, mu, nu,
                               params if params is not None else mu)
        return updates, AdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def fused_adagrad(learning_rate=1e-2, eps: float = 1e-10,
                  weight_decay: float = 0.0,
                  state_dtype=jnp.float32) -> optax.GradientTransformation:
    """Reference: csrc/adagrad/cpu_adagrad.cpp / ops/adagrad/cpu_adagrad.py."""

    class AdagradState(NamedTuple):
        count: jnp.ndarray
        accum: any

    def init(params):
        return AdagradState(count=jnp.zeros((), jnp.int32),
                            accum=jax.tree.map(
                                lambda p: jnp.zeros_like(p, dtype=state_dtype), params))

    def update(grads, state, params=None):
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        accum = jax.tree.map(lambda a, g: a + jnp.square(g.astype(a.dtype)),
                             state.accum, grads)

        def upd(g, a, p):
            step = g.astype(a.dtype) / (jnp.sqrt(a) + eps)
            if weight_decay:
                step = step + weight_decay * p.astype(step.dtype)
            return (-lr * step).astype(p.dtype)

        updates = jax.tree.map(upd, grads, accum,
                               params if params is not None else grads)
        return updates, AdagradState(count=count, accum=accum)

    return optax.GradientTransformation(init, update)
