"""Host-side (CPU) Adam/Adagrad over numpy buffers — the ZeRO-Offload
optimizer.

Reference analogue: ``deepspeed/ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``
driving csrc/adam/cpu_adam.cpp) and ``ops/adagrad/cpu_adagrad.py``. The
optimizer owns flat fp32 master/momentum buffers in host DRAM and calls the
native SIMD kernel per step; a numpy fallback keeps the semantics when the
native build is unavailable (the reference hard-fails instead — builder
``is_compatible`` gating).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from .op_builder import get_native_lib


def f32_to_bf16_bits(src: np.ndarray, out: Optional[np.ndarray] = None
                     ) -> np.ndarray:
    """Round-to-nearest-even fp32 -> bf16 bit pattern (uint16). The single
    Python home of the conversion the native kernel also performs
    (csrc/cpu_adam.cpp ds_adam_step_bf16)."""
    bits = np.ascontiguousarray(src, np.float32).view(np.uint32)
    rounding = 0x7FFF + ((bits >> 16) & 1)
    res = ((bits + rounding) >> 16).astype(np.uint16)
    if out is not None:
        out[:] = res
        return out
    return res


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u16p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class DeepSpeedCPUAdam:
    """Fused Adam/AdamW over flat host fp32 arrays.

    ``step(params, grads, exp_avg, exp_avg_sq)`` updates all four in place.
    All arrays must be contiguous float32 of equal length.
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._lib = get_native_lib()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def step(self, params: np.ndarray, grads: np.ndarray,
             exp_avg: np.ndarray, exp_avg_sq: np.ndarray,
             params_bf16: Optional[np.ndarray] = None,
             lr: Optional[float] = None, step: Optional[int] = None):
        if step is None:
            self.step_count += 1
            step = self.step_count
        lr = self.lr if lr is None else float(lr)
        b1, b2 = self.betas
        n = params.size
        if self._lib is not None:
            if params_bf16 is not None:
                self._lib.ds_adam_step_bf16(
                    _f32p(params), _u16p(params_bf16), _f32p(grads),
                    _f32p(exp_avg), _f32p(exp_avg_sq), n, lr, b1, b2,
                    self.eps, self.weight_decay, int(self.adamw_mode), step)
            else:
                self._lib.ds_adam_step(
                    _f32p(params), _f32p(grads), _f32p(exp_avg),
                    _f32p(exp_avg_sq), n, lr, b1, b2, self.eps,
                    self.weight_decay, int(self.adamw_mode), step)
            return
        # ---- numpy fallback (same math) --------------------------------
        g = grads
        if self.weight_decay != 0.0:
            if self.adamw_mode:
                params *= 1.0 - lr * self.weight_decay
            else:
                g = g + self.weight_decay * params
        exp_avg *= b1
        exp_avg += (1 - b1) * g
        exp_avg_sq *= b2
        exp_avg_sq += (1 - b2) * g * g
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        denom = np.sqrt(exp_avg_sq) / np.sqrt(bc2) + self.eps
        params -= (lr / bc1) * exp_avg / denom
        if params_bf16 is not None:
            f32_to_bf16_bits(params, out=params_bf16)


class DeepSpeedCPUAdagrad:
    """Fused Adagrad over flat host fp32 arrays (reference
    ops/adagrad/cpu_adagrad.py:141)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = get_native_lib()

    def step(self, params: np.ndarray, grads: np.ndarray,
             exp_avg_sq: np.ndarray, lr: Optional[float] = None):
        lr = self.lr if lr is None else float(lr)
        if self._lib is not None:
            self._lib.ds_adagrad_step(
                _f32p(params), _f32p(grads), _f32p(exp_avg_sq),
                params.size, lr, self.eps, self.weight_decay)
            return
        g = grads
        if self.weight_decay != 0.0:
            g = g + self.weight_decay * params
        exp_avg_sq += g * g
        params -= lr * g / (np.sqrt(exp_avg_sq) + self.eps)
