"""Namespace parity with the reference's 1-bit op backends
(``deepspeed/ops/adam/onebit`` tier) — the implementations live with the
fp16 runtime, where the compressed exchange is wired into the engine.
"""

from ...runtime.fp16.onebit.adam import OnebitAdam
from ...runtime.fp16.onebit.lamb import OnebitLamb
from ...runtime.fp16.onebit.zoadam import ZeroOneAdam

__all__ = ["OnebitAdam", "OnebitLamb", "ZeroOneAdam"]
