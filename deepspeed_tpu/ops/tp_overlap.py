"""Overlapping the post-attention tp collective with the MLP gemm.

At decode batch sizes the tensor-parallel all-reduce after the attention
output projection is pure exposed latency: the tokens-per-step tensor is
tiny, so the collective is latency-bound, and in the sequential-residual
block nothing can run until it lands. The NeoX parallel-residual block
(``x + attn(ln1 x) + ffn(ln2 x)``) breaks that dependence — the MLP gemm
reads ``ln2(x)`` and is completely independent of the attention branch,
so its compute can hide the collective's wire time.

Rather than hand-scheduling, we decompose the all-reduce so XLA's
latency-hiding scheduler can do the overlap itself:

  * ``defer_attn_allreduce`` pins the attention-branch output to a
    hidden-sharded layout ``P(None, None, "tp")``. Under GSPMD the
    psum that would have followed the output projection becomes a
    REDUCE-SCATTER into that layout, and the later residual add against
    replicated operands forces the matching ALL-GATHER. Between the two
    halves sits the (independent) MLP gemm — an async-start/async-done
    pair the scheduler slots compute into, instead of one blocking
    all-reduce. The decomposition is a relayout of the same sum: at
    tp=2 the reduction is a single two-term add either way, so greedy
    decode stays bit-identical (gated by test_serving's tp=2 parity
    test); at higher degrees ring reassociation applies, same as any
    psum implementation choice.

  * ``ring_allreduce`` is the explicit latency-optimized form for when
    GSPMD must not be trusted with the decomposition: a shard_map
    reduce-scatter + all-gather ring built from ``ppermute`` (the same
    collective idiom as ops/ring_attention.py). 2(n-1) hops of 1/n-sized
    messages — the bandwidth-optimal schedule — with each hop's partial
    add available for overlap.

  * ``decode_step_overlap_model`` is the CPU proxy for the acceptance
    gate: on hosts without ICI the overlap cannot be timed for real, so
    the bench reports the analytic step model
    ``attn + max(collective, mlp)`` vs ``attn + collective + mlp``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map  # check_vma/check_rep version shim


def overlap_supported(y, mesh: Optional[Mesh], axis_name: str = "tp") -> bool:
    """The RS/AG decomposition needs a real tp axis and a hidden dim it
    divides; anything else keeps the plain psum (constraint would fail or
    be the identity)."""
    if mesh is None or y.ndim != 3:
        return False
    tp = dict(mesh.shape).get(axis_name, 1)
    return tp > 1 and y.shape[-1] % tp == 0


def defer_attn_allreduce(y, axis_name: str = "tp",
                         mesh: Optional[Mesh] = None):
    """Constrain the attention-branch output [B, S, D] to hidden-sharded
    ``P(None, None, tp)`` so GSPMD splits its pending psum into
    reduce-scatter (here) + all-gather (at the residual add), leaving the
    MLP gemm free to run between them. No-op when the mesh has no tp
    axis or D doesn't divide — the caller's math is unchanged either
    way (the constraint is a layout statement, not an op)."""
    if mesh is None:
        from ..parallel.mesh import get_constraint_mesh
        mesh = get_constraint_mesh()
    if not overlap_supported(y, mesh, axis_name):
        return y
    return jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, P(None, None, axis_name)))


def _ring_local(x, *, axis_name: str, n: int):
    """Per-shard reduce-scatter + all-gather ring over leading-dim chunks.
    x arrives REPLICATED per shard holding that shard's partial sum; the
    return is the full sum, replicated again."""
    r = jax.lax.axis_index(axis_name)
    chunks = jnp.stack(jnp.split(x, n, axis=0))        # [n, rows/n, ...]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_step(carry, t):
        acc, chunks = carry
        # the acc arriving from shard r-1 carries chunk (r - t - 1) % n;
        # add our own contribution to the same chunk. After n-1 hops
        # shard r holds the COMPLETE sum of chunk r.
        idx = (r - t - 1) % n
        acc = jax.lax.ppermute(acc, axis_name, perm) + chunks[idx]
        return (acc, chunks), None

    acc0 = chunks[(r - 1) % n]                          # t=0 seed, no hop
    (acc, _), _ = jax.lax.scan(rs_step, (acc0, chunks),
                               jnp.arange(1, n))

    def ag_step(carry, t):
        blk, out = carry
        blk = jax.lax.ppermute(blk, axis_name, perm)
        src = (r - t) % n                               # origin of blk now
        out = jax.lax.dynamic_update_index_in_dim(out, blk, src, 0)
        return (blk, out), None

    out = jnp.zeros_like(chunks)
    out = jax.lax.dynamic_update_index_in_dim(out, acc, r, 0)
    (_, out), _ = jax.lax.scan(ag_step, (acc, out), jnp.arange(1, n))
    return out.reshape(x.shape)


def ring_allreduce(x, mesh: Mesh, axis_name: str = "tp"):
    """Explicit ring all-reduce of per-shard partial sums: x [rows, ...]
    is one partial per tp shard (replicated layout in, replicated out);
    rows must divide by the ring size. Bitwise == psum at n=2 (one add
    per element either way); at n>2 the ring's reassociation applies."""
    n = dict(mesh.shape).get(axis_name, 1)
    if n == 1:
        return x
    if x.shape[0] % n != 0:
        raise ValueError(
            f"ring_allreduce needs rows % ring == 0, got {x.shape[0]} "
            f"rows on a {n}-wide {axis_name!r} axis")
    fn = partial(_ring_local, axis_name=axis_name, n=n)
    spec = P(*([None] * x.ndim))
    return shard_map(fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
                     check_vma=False)(x)


def decode_step_overlap_model(t_attn: float, t_collective: float,
                              t_mlp: float) -> Dict[str, float]:
    """Analytic decode-step model for the overlap win, used as the CPU
    proxy (no ICI to time): the unhidden baseline serializes
    attn -> collective -> mlp; the overlapped step runs the collective
    under the MLP gemm. Returns both step times and their ratio."""
    unhidden = t_attn + t_collective + t_mlp
    overlapped = t_attn + max(t_collective, t_mlp)
    return {
        "t_attn_s": float(t_attn),
        "t_collective_s": float(t_collective),
        "t_mlp_s": float(t_mlp),
        "step_unhidden_s": float(unhidden),
        "step_overlapped_s": float(overlapped),
        "overlap_ratio": float(overlapped / unhidden) if unhidden else 1.0,
        "hidden_s": float(unhidden - overlapped),
    }
