"""Ring attention: context parallelism by rotating KV blocks around the
``sp`` ring.

The second long-context strategy next to Ulysses (models/gpt.py
``sequence_parallel``): Ulysses all-to-alls sequence<->head shards, so its
parallel degree is capped by (and must divide) the head count; ring
attention keeps q sequence-sharded and passes the K/V shard around the
ring with ``ppermute``, accumulating blockwise-softmax partials — any ring
size works, per-chip memory is O(S/sp), and each hop's compute hides the
next hop's ICI transfer (the blockwise-parallel-transformer/ring-attention
construction; reference v0.6.6 has no context parallelism at all, SURVEY
§2.10).

Everything lives in one ``shard_map`` region differentiated through a
``lax.scan`` over ring steps — collectives (ppermute) transpose cleanly, so
the backward pass is the reverse rotation, no custom VJP needed.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from ..utils.jax_compat import shard_map  # check_vma/check_rep + jax-version shim
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale, causal):
    """One blockwise attention partial: returns (scores_max [B,H,Sq],
    exp-sum [B,H,Sq], weighted values [B,Sq,H,D]) in fp32."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
    return m, l, acc.astype(jnp.float32)


def _ring_local(q, k, v, *, axis_name, ring_size, scale, causal):
    """Per-shard body: q/k/v [B, S/sp, H, D] local chunks."""
    r = jax.lax.axis_index(axis_name)
    chunk = q.shape[1]
    base = jnp.arange(chunk)
    q_pos = r * chunk + base
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def combine(state, t, k_t, v_t):
        m, l, acc = state
        src = (r - t) % ring_size          # origin rank of the current kv
        k_pos = src * chunk + base
        bm, bl, bacc = _block_attend(q, k_t, v_t, q_pos, k_pos, scale,
                                     causal)
        m_new = jnp.maximum(m, bm)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(bm - m_new)
        l = l * c_old + bl * c_new
        acc = acc * jnp.moveaxis(c_old, 1, -1)[..., None] \
            + bacc * jnp.moveaxis(c_new, 1, -1)[..., None]
        return m_new, l, acc

    def step(carry, t):
        # rotate FIRST (steps 1..ring-1): the local block was consumed
        # before the scan, and this layout never pays for a final rotation
        # whose result would be discarded
        kv, state = carry
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm),
                          kv)
        state = combine(state, t, *kv)
        return (kv, state), None

    b, sq, h, d = q.shape
    state0 = (jnp.full((b, h, sq), NEG_INF, jnp.float32),
              jnp.zeros((b, h, sq), jnp.float32),
              jnp.zeros((b, sq, h, d), jnp.float32))
    state0 = combine(state0, 0, k, v)      # local block, no transfer
    (_, (m, l, acc)), _ = jax.lax.scan(
        step, ((k, v), state0), jnp.arange(1, ring_size))
    l_safe = jnp.where(l == 0, 1.0, l)
    out = acc / jnp.moveaxis(l_safe, 1, -1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, *, axis_name: str = "sp",
                   scale: Optional[float] = None, causal: bool = True,
                   batch_axis: str = "dp"):
    """q, k, v: [B, S, H, D] global arrays (S sharded over `axis_name`,
    B over `batch_axis`) -> [B, S, H, D] attention output, same sharding."""
    ring = dict(mesh.shape).get(axis_name, 1)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if ring == 1:
        m, l, acc = _block_attend(
            q, k, v, jnp.arange(q.shape[1]), jnp.arange(k.shape[1]),
            scale, causal)
        l_safe = jnp.where(l == 0, 1.0, l)
        return (acc / jnp.moveaxis(l_safe, 1, -1)[..., None]).astype(q.dtype)
    dp = dict(mesh.shape).get(batch_axis, 1)
    b_axis = batch_axis if q.shape[0] % max(dp, 1) == 0 else None
    spec = P(b_axis, axis_name, None, None)
    fn = partial(_ring_local, axis_name=axis_name, ring_size=ring,
                 scale=scale, causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
