"""Standalone block-sparse matmul: SDD / DSD / DDS.

Reference analogue: ``deepspeed/ops/sparse_attention/matmul.py:214-995``
(triton-backed ``MatMul`` usable outside attention — the building block
users compose into custom sparse kernels). The TPU formulation is
gather/scatter over the static block layout expressed in XLA: nonzero
block coordinates are extracted from the (static, host-side) layout at
construction, the hot loop is one batched [nnz, block, block] einsum that
XLA tiles onto the MXU, and DSD/DDS row-accumulation is a segment-sum
over the static row ids. The fused attention path keeps its dedicated
Pallas kernels (sparse_self_attention.py) — this op exists for everything
else the reference's generic matmul serves (sparse MLPs, block-sparse
routing, custom attention variants).

Sparse operands travel in the reference's packed value layout:
``[batch, nnz, block, block]`` where ``nnz`` enumerates the layout's
nonzero (head, row, col) blocks in ``np.nonzero`` order (row-major per
head) — the same convention the reference's triton kernels use, so
packed tensors port across.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class MatMul:
    """Block-sparse matmul over a static block ``layout``.

    layout: [H, M_blocks, N_blocks] 0/1 (numpy or array-like; static).
    block:  square block size (TPU-friendly multiples of 8; 128 rides the
            MXU tile exactly).
    mode:   'sdd' — dense @ dense -> sparse (packed [B, nnz, blk, blk])
            'dsd' — sparse @ dense -> dense
            'dds' — dense @ sparse -> dense
    trans_a / trans_b transpose the last two dims of the respective
    operand before the multiply (reference MatMul flags).

    Dense operands are [B, H, R, C]; a batch whose H dim is 1 broadcasts
    over the layout's H.
    """

    def __init__(self, layout, block: int, mode: str,
                 trans_a: bool = False, trans_b: bool = False):
        if mode not in ("sdd", "dsd", "dds"):
            raise ValueError(f"mode must be sdd/dsd/dds, got {mode!r}")
        layout = np.asarray(layout)
        if layout.ndim != 3:
            raise ValueError(f"layout must be [H, M_blocks, N_blocks]; "
                             f"got shape {layout.shape}")
        if (mode == "dsd" and trans_a) or (mode == "dds" and trans_b):
            raise NotImplementedError(
                "transposing the PACKED sparse operand needs a transposed "
                "layout (blocks move (i,j)->(j,i)), not just per-block "
                "transposes — construct a MatMul over layout.transpose("
                "0, 2, 1) with swapped operand roles instead")
        if block < 1:
            raise ValueError("block must be positive")
        self.layout = (layout != 0)
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        h, i, j = np.nonzero(self.layout)
        if h.size == 0:
            raise ValueError("layout has no nonzero blocks")
        self.nnz = int(h.size)
        self._h = jnp.asarray(h, jnp.int32)
        self._i = jnp.asarray(i, jnp.int32)
        self._j = jnp.asarray(j, jnp.int32)
        self._mblocks = int(self.layout.shape[1])
        self._nblocks = int(self.layout.shape[2])
        self._heads = int(self.layout.shape[0])

    # ------------------------------------------------------------- helpers
    def _dense_blocks(self, x, rows: jnp.ndarray, heads: jnp.ndarray,
                      n_blocks: int, what: str) -> jnp.ndarray:
        """[B, H, R, C] -> per-nnz row-blocks [B, nnz, block, C].

        The row dim is validated against the layout: XLA clamps
        out-of-range gather indices, so an undersized or wrongly-oriented
        operand would otherwise produce finite-but-wrong numbers."""
        b, hh, r, c = x.shape
        blk = self.block
        if r != n_blocks * blk:
            raise ValueError(
                f"{what}: dense operand dim {r} does not match the "
                f"layout's {n_blocks} blocks of {blk} "
                f"(= {n_blocks * blk}); check operand orientation")
        if hh not in (1, self._heads):
            raise ValueError(
                f"{what}: operand has {hh} heads, layout has "
                f"{self._heads}")
        xb = x.reshape(b, hh, n_blocks, blk, c)
        heads = jnp.zeros_like(heads) if hh == 1 else heads
        return xb[:, heads, rows]                    # [B, nnz, blk, C]

    @staticmethod
    def _t(x, do):
        return jnp.swapaxes(x, -1, -2) if do else x

    # ---------------------------------------------------------------- call
    def __call__(self, a, b):
        blk, mode = self.block, self.mode
        if mode == "sdd":
            A = self._t(a, self.trans_a)
            B = self._t(b, self.trans_b)
            if A.shape[-2] != self._mblocks * blk \
                    or B.shape[-1] != self._nblocks * blk:
                raise ValueError(
                    f"sdd: operands {A.shape} x {B.shape} do not match "
                    f"layout [{self._mblocks}x{self._nblocks}] blocks of "
                    f"{blk}")
            ab = self._dense_blocks(A, self._i, self._h,
                                    self._mblocks, "sdd lhs")
            bt = jnp.swapaxes(B, -1, -2)                  # [B,H,N,K]
            bb = self._dense_blocks(bt, self._j, self._h,
                                    self._nblocks, "sdd rhs")
            return jnp.einsum("znik,znjk->znij", ab, bb)

        if mode == "dsd":
            # packed a [B, nnz, blk, blk] @ dense b [B, H, K, N]
            # (trans_a on the packed side was rejected at construction)
            A = a
            B = self._t(b, self.trans_b)
            if A.shape[1] != self.nnz:
                raise ValueError(
                    f"dsd: packed operand has {A.shape[1]} blocks, layout "
                    f"has {self.nnz}")
            bb = self._dense_blocks(B, self._j, self._h,
                                    self._nblocks, "dsd rhs")
            prod = jnp.einsum("znij,znjc->znic", A, bb)   # [B,nnz,blk,N]
            seg = self._h * self._mblocks + self._i
            out = jax.ops.segment_sum(
                jnp.swapaxes(prod, 0, 1), seg,
                num_segments=self._heads * self._mblocks)
            out = jnp.swapaxes(out, 0, 1)  # [B, H*Mb, blk, N]
            bsz, _, _, n = out.shape
            return out.reshape(bsz, self._heads, self._mblocks * blk, n)

        # dds: dense a [B, H, M, K] @ packed b [B, nnz, blk, blk]
        # (trans_b on the packed side was rejected at construction)
        A = self._t(a, self.trans_a)
        B = b
        if B.shape[1] != self.nnz:
            raise ValueError(
                f"dds: packed operand has {B.shape[1]} blocks, layout has "
                f"{self.nnz}")
        at = jnp.swapaxes(A, -1, -2)                      # [B,H,K,M]
        ab = self._dense_blocks(at, self._i, self._h,
                                self._mblocks, "dds lhs")
        prod = jnp.einsum("znkm,znkj->znmj", ab, B)       # [B,nnz,M,blk]
        seg = self._h * self._nblocks + self._j
        out = jax.ops.segment_sum(
            jnp.swapaxes(prod, 0, 1), seg,
            num_segments=self._heads * self._nblocks)
        out = jnp.swapaxes(out, 0, 1)  # [B, H*Nb, M, blk]
        bsz, _, m, _ = out.shape
        out = out.reshape(bsz, self._heads, self._nblocks, m, blk)
        return jnp.swapaxes(out, 2, 3).reshape(
            bsz, self._heads, m, self._nblocks * blk)

    # ------------------------------------------------------------ packing
    def pack(self, dense) -> jnp.ndarray:
        """Dense [B, H, M, N] -> packed [B, nnz, blk, blk] (layout order)."""
        blk = self.block
        bsz, hh, m, n = dense.shape
        if m != self._mblocks * blk or n != self._nblocks * blk:
            raise ValueError(
                f"pack: dense [{m}x{n}] does not match layout "
                f"[{self._mblocks}x{self._nblocks}] blocks of {blk}")
        if hh not in (1, self._heads):
            raise ValueError(f"pack: operand has {hh} heads, layout has "
                             f"{self._heads}")
        xb = dense.reshape(bsz, hh, m // blk, blk, n // blk, blk)
        xb = jnp.moveaxis(xb, 4, 3)    # [B, H, Mb, Nb, blk, blk]
        heads = (jnp.zeros_like(self._h) if hh == 1 else self._h)
        return xb[:, heads, self._i, self._j]

    def unpack(self, packed, dtype=None) -> jnp.ndarray:
        """Packed [B, nnz, blk, blk] -> dense [B, H, M, N] with zeros in
        the empty blocks."""
        blk = self.block
        bsz = packed.shape[0]
        out = jnp.zeros((bsz, self._heads, self._mblocks, self._nblocks,
                         blk, blk), packed.dtype if dtype is None else dtype)
        out = out.at[:, self._h, self._i, self._j].set(packed)
        out = jnp.moveaxis(out, 3, 4)
        return out.reshape(bsz, self._heads, self._mblocks * blk,
                           self._nblocks * blk)
