"""Block-sparse attention layout configurations.

Reference analogue: ``deepspeed/ops/sparse_attention/sparsity_config.py``
(683 LoC) — the same class vocabulary and parameters: ``SparsityConfig``
base (:9), ``DenseSparsityConfig`` (:64), ``FixedSparsityConfig`` (:94,
Sparse Transformers arXiv:1904.10509), ``VariableSparsityConfig`` (:243),
``BigBirdSparsityConfig`` (:421, arXiv:2007.14062),
``BSLongformerSparsityConfig`` (:559, arXiv:2004.05150).

A layout is a ``[num_heads, num_blocks, num_blocks]`` 0/1 ndarray: entry
(h, i, j) says whether query block i attends to key block j for head h.
Layouts are built host-side in numpy (they are tiny and static per seq_len)
and consumed by the Pallas block-sparse kernel
(sparse_self_attention.py), which skips dead (q-block, k-block) tiles —
the TPU equivalent of the reference's Triton LUT machinery
(matmul.py:214-995).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

# Deterministic seed for random-block layouts: every host must build the
# SAME layout or data-parallel replicas would compute different functions
# (the reference uses the unseeded global `random`, sparsity_config.py:6 —
# safe there only because torch broadcasts module buffers from rank 0).
LAYOUT_SEED = 0x5EED


def _check_attention_mode(attention: str) -> None:
    if attention not in ("unidirectional", "bidirectional"):
        raise ValueError(
            f"attention must be 'unidirectional' or 'bidirectional', "
            f"got {attention!r}")


def _check_global_ranges(starts: List[int], ends: Optional[List[int]]) -> None:
    """Validate paired [start, end) global-block ranges."""
    if ends is None:
        return
    if len(starts) != len(ends):
        raise ValueError(
            f"global_block_indices has {len(starts)} entries but "
            f"global_block_end_indices has {len(ends)} — they pair up "
            f"as [start, end) ranges")
    for s, e in zip(starts, ends):
        if s >= e:
            raise ValueError(
                f"empty global range: start {s} >= end {e}")



class SparsityConfig:
    """Base: block size + per-head layout bookkeeping (reference :9-61)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len={seq_len} is not a multiple of the layout block "
                f"size ({self.block}); pad the sequence first "
                f"(SparseAttentionUtils.pad_to_block_size)")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    # -- shared pattern helpers (hoisted; the reference duplicates these
    # across config classes) ------------------------------------------------
    def _set_sliding_window(self, h: int, layout: np.ndarray,
                            num_window_blocks: int) -> np.ndarray:
        num_blocks = layout.shape[1]
        if num_blocks < num_window_blocks:
            raise ValueError(
                f"sliding window spans {num_window_blocks} blocks but the "
                f"sequence only has {num_blocks} blocks per row")
        w = num_window_blocks // 2
        for row in range(num_blocks):
            layout[h, row, max(0, row - w):min(row + w + 1, num_blocks)] = 1
        return layout

    def _set_random(self, h: int, layout: np.ndarray, num_random_blocks: int,
                    unidirectional: bool) -> np.ndarray:
        num_blocks = layout.shape[1]
        if num_blocks < num_random_blocks:
            raise ValueError(
                f"cannot place {num_random_blocks} random blocks in a row "
                f"of only {num_blocks} blocks")
        rng = np.random.default_rng(LAYOUT_SEED + h)
        for row in range(num_blocks):
            hi = row + 1 if unidirectional else num_blocks
            k = min(num_random_blocks, hi)
            cols = rng.choice(hi, size=k, replace=False)
            layout[h, row, cols] = 1
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks live — for comparison/debug (reference :64-93)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (reference :94-241)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks ({num_local_blocks}) must be a multiple "
                f"of num_global_blocks ({num_global_blocks}) so global "
                f"stripes tile the local windows evenly")
        self.num_global_blocks = num_global_blocks
        _check_attention_mode(attention)
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal_global_attention writes full rows and is only "
                "meaningful for attention='bidirectional'")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "num_different_global_patterns > 1 requires "
                "different_layout_per_head=True (otherwise every head "
                "shares one layout and the variants are unreachable)")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns="
                f"{num_different_global_patterns} exceeds the distinct "
                f"global-stripe offsets available per local window "
                f"({num_local_blocks // num_global_blocks})")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        for start in range(0, num_blocks, self.num_local_blocks):
            end = min(start + self.num_local_blocks, num_blocks)
            for row in range(start, end):
                hi = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:hi] = 1
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        first_global = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns) * self.num_global_blocks
        end = num_blocks - (num_blocks % self.num_local_blocks)
        for i in range(first_global, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        if end < num_blocks:  # short trailing window
            start = min(end + first_global, num_blocks - self.num_global_blocks)
            stop = start + self.num_global_blocks
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:stop] = 1
            if self.horizontal_global_attention:
                layout[h, start:stop, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + explicit global indices + random blocks
    (reference :243-419)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        _check_global_ranges(self.global_block_indices,
                             global_block_end_indices)
        self.global_block_end_indices = global_block_end_indices
        _check_attention_mode(attention)
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal_global_attention writes full rows and is only "
                "meaningful for attention='bidirectional'")
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h: int, layout: np.ndarray):
        return self._set_random(h, layout, self.num_random_blocks,
                                unidirectional=False)

    def set_local_layout(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        start = 0
        end = 0
        block_size = self.local_window_blocks[-1]
        for block_size in self.local_window_blocks:
            end = min(end + block_size, num_blocks)
            for row in range(start, end):
                hi = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:hi] = 1
            start += block_size
        for i in range(start, num_blocks, block_size):
            end = min(i + block_size, num_blocks)
            for row in range(i, end):
                hi = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, i:hi] = 1
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
                    first_row = 0 if self.attention == "bidirectional" else idx
                    layout[h, first_row:, idx] = 1
        else:
            for start_idx, end_idx in zip(self.global_block_indices,
                                          self.global_block_end_indices):
                if start_idx < num_blocks:
                    end_idx = min(end_idx, num_blocks)
                    if self.horizontal_global_attention:
                        layout[h, start_idx:end_idx, :] = 1
                    first_row = 0 if self.attention == "bidirectional" else start_idx
                    layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (reference :421-556)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        _check_attention_mode(attention)
        self.attention = attention

    def set_random_layout(self, h: int, layout: np.ndarray):
        return self._set_random(
            h, layout, self.num_random_blocks,
            unidirectional=(self.attention == "unidirectional"))

    def set_sliding_window_layout(self, h: int, layout: np.ndarray):
        return self._set_sliding_window(h, layout,
                                        self.num_sliding_window_blocks)

    def set_global_layout_itc(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                f"num_global_blocks ({self.num_global_blocks}) exceeds the "
                f"{num_blocks} blocks in a row")
        layout[h, 0:self.num_global_blocks, :] = 1
        layout[h, :, 0:self.num_global_blocks] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + explicit global indices
    (reference :559-683)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        _check_attention_mode(attention)
        self.attention = attention
        _check_global_ranges(self.global_block_indices,
                             global_block_end_indices)
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h: int, layout: np.ndarray):
        return self._set_sliding_window(h, layout,
                                        self.num_sliding_window_blocks)

    def set_global_layout(self, h: int, layout: np.ndarray):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    layout[h, idx, :] = 1
                    layout[h, :, idx] = 1
        else:
            for start_idx, end_idx in zip(self.global_block_indices,
                                          self.global_block_end_indices):
                if start_idx < num_blocks:
                    end_idx = min(end_idx, num_blocks)
                    layout[h, start_idx:end_idx, :] = 1
                    layout[h, :, start_idx:end_idx] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
