"""Block-sparse attention (reference: deepspeed/ops/sparse_attention/)."""

from .matmul import MatMul
from .sparse_self_attention import sparse_attention
from .sparsity_config import (BigBirdSparsityConfig,
                              BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)
