"""Block-sparse self attention, Pallas/TPU.

Reference analogue: ``deepspeed/ops/sparse_attention/sparse_self_attention.py``
(:13-165, the QK^T -> masked softmax -> PV pipeline over a block layout) and
the Triton block-sparse matmul/softmax machinery it drives
(``matmul.py:214-995``, layout LUTs at ``matmul.py:613-674``).

TPU-native design: the layout is compiled host-side into per-(head, q-tile)
look-up tables of *live* k-tiles, and the kernel grid iterates only over
live tiles — the LUT is a scalar-prefetch argument, so the BlockSpec index
maps themselves read it to decide which K/V tile to DMA. Dead tiles are
never fetched or computed: both FLOPs and HBM traffic scale with layout
density (the property the reference gets from Triton's LUT kernels). Within
a live kernel tile, the fine ``SparsityConfig.block`` mask is applied
elementwise.

Unidirectional layouts additionally get an exact elementwise causal mask
(the reference is causal only at block granularity).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..pallas._utils import interpret_mode
from .sparsity_config import SparsityConfig

NEG_INF = -1e30


def _expand_block_mask(fine, cb, bq, bk):
    """[fq, fk] 0/1 block mask -> [bq, bk] elementwise bool. Expansion is
    done with two tiny 0/1 matmuls (E_r @ fine @ E_c) instead of
    repeat/reshape — Mosaic can't lower the cross-lane reshape a
    ``jnp.repeat`` would need, but eats these matmuls on the MXU."""
    fq, fk = fine.shape
    f = fine.astype(jnp.float32)
    er = (jax.lax.broadcasted_iota(jnp.int32, (bq, fq), 0) // cb
          == jax.lax.broadcasted_iota(jnp.int32, (bq, fq), 1)
          ).astype(jnp.float32)
    ec = (jax.lax.broadcasted_iota(jnp.int32, (fk, bk), 1) // cb
          == jax.lax.broadcasted_iota(jnp.int32, (fk, bk), 0)
          ).astype(jnp.float32)
    m = jax.lax.dot(er, jax.lax.dot(f, ec,
                                    preferred_element_type=jnp.float32),
                    preferred_element_type=jnp.float32)
    return m > 0.5


def _tile_mask(fine_tile, cb, bq, bk, qi, kj, causal):
    mask = _expand_block_mask(fine_tile, cb, bq, bk)
    if causal:
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = jnp.logical_and(mask, rows >= cols)
    return mask


# ---------------------------------------------------------------------------
# Kernels. Grid: (B, H, n_row_tiles, LUT_len); the innermost dim walks the
# LUT of live column tiles. Scalar-prefetch args: lut [H, n, L], cnt [H, n].
# ---------------------------------------------------------------------------

def _fwd_kernel(lut_ref, cnt_ref, fine_ref, q_ref, k_ref, v_ref, *rest,
                scale, cb, block_q, block_k, causal, use_mask=False):
    if use_mask:
        kvm_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    hi, qi, t = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(t < cnt_ref[hi, qi])
    def _compute():
        kj = lut_ref[hi, qi, t]
        q = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(fine_ref[0, 0, 0], cb, block_q, block_k, qi, kj,
                          causal)
        if use_mask:
            # key-padding mask (reference SparseSelfAttention
            # key_padding_mask): masked keys drop out of this k-tile
            mask = jnp.logical_and(mask, (kvm_ref[0, 0] > 0)[None, :])
        s = jnp.where(mask, s, NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe[:, None]), 0.0)
        corr = jnp.where(m_prev <= NEG_INF / 2, 1.0, jnp.exp(m_prev - m_safe))
        m_scr[...] = m_new
        l_scr[...] = l_prev * corr + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + jax.lax.dot(
            p, vb, preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        m = m_scr[...]
        lse_ref[0, 0] = jnp.where(m <= NEG_INF / 2, NEG_INF,
                                  m + jnp.log(l_safe))[:, None]


def _bwd_dq_kernel(lut_ref, cnt_ref, fine_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, *rest, scale, cb,
                   block_q, block_k, causal, use_mask=False):
    if use_mask:
        kvm_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    hi, qi, t = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(t < cnt_ref[hi, qi])
    def _compute():
        kj = lut_ref[hi, qi, t]
        q = q_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(fine_ref[0, 0, 0], cb, block_q, block_k, qi, kj,
                          causal)
        if use_mask:
            mask = jnp.logical_and(mask, (kvm_ref[0, 0] > 0)[None, :])
        # dead-row guard: a fully-masked query has lse = -inf; exp(s - lse)
        # would overflow instead of vanishing
        mask = jnp.logical_and(mask, (lse > NEG_INF / 2)[:, None])
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot(
            ds, kb, preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(lut_ref, cnt_ref, fine_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, *rest, scale, cb, block_q, block_k,
                    causal, use_mask=False):
    if use_mask:
        kvm_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    hi, ki, t = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(t < cnt_ref[hi, ki])
    def _compute():
        qi = lut_ref[hi, ki, t]
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0].astype(jnp.float32)
        dob = do_ref[0, 0].astype(jnp.float32)
        lseb = lse_ref[0, 0, :, 0]
        deltab = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(fine_ref[0, 0, 0], cb, block_q, block_k, qi, ki,
                          causal)
        if use_mask:
            mask = jnp.logical_and(mask, (kvm_ref[0, 0] > 0)[None, :])
        mask = jnp.logical_and(mask, (lseb > NEG_INF / 2)[:, None])
        p = jnp.where(mask, jnp.exp(s - lseb[:, None]), 0.0)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab[:, None]) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Host-side: layout compilation (LUTs) and pallas_call orchestration
# ---------------------------------------------------------------------------

def _kernel_block(s: int, cb: int, target: int = 128) -> int:
    """Largest multiple of the layout block <= target that divides S."""
    best = cb
    m = cb
    while m <= target:
        if s % m == 0:
            best = m
        m += cb
    return best


class _CompiledLayout:
    """LUTs + fine tile tensor for one (layout, seq_len, block) combo —
    the analogue of the reference's ``make_lut`` results cached on the
    sparse matmul objects (matmul.py:613-674)."""

    def __init__(self, fine: np.ndarray, cb: int, bq: int, bk: int,
                 causal: bool):
        h, nb, _ = fine.shape
        if causal:
            fine = np.tril(np.ones((nb, nb), fine.dtype))[None] * fine
        self.cb, self.bq, self.bk = cb, bq, bk
        fq, fk = bq // cb, bk // cb
        nq, nk = nb // fq, nb // fk
        # LUTs/tiles stay NUMPY: the layout cache outlives any one trace,
        # and a jnp constant created inside a jitted first call would be a
        # staged tracer — reusing it from the cache in the next trace
        # raises UnexpectedTracerError. Call sites convert per trace.
        # fine tiles: [H, nq, nk, fq, fk]
        self.fine_tiles = (fine.reshape(h, nq, fq, nk, fk)
                           .transpose(0, 1, 3, 2, 4).astype(np.int32))
        coarse = fine.reshape(h, nq, fq, nk, fk).max(axis=(2, 4))
        # row-major LUT (fwd, dq): live k-tiles per (h, qi)
        self.lut_k, self.cnt_k = self._build_lut(coarse)
        # column-major LUT (dkv): live q-tiles per (h, ki)
        self.lut_q, self.cnt_q = self._build_lut(coarse.transpose(0, 2, 1))
        self.density = float(coarse.mean())

    @staticmethod
    def _build_lut(coarse: np.ndarray):
        h, n, m = coarse.shape
        counts = coarse.sum(axis=2).astype(np.int32)
        L = max(int(counts.max()), 1)
        lut = np.zeros((h, n, L), np.int32)
        for hh in range(h):
            for i in range(n):
                live = np.nonzero(coarse[hh, i])[0]
                lut[hh, i, :len(live)] = live
        return lut, counts


def _sparse_fwd(q, k, v, layout: _CompiledLayout, causal, scale, kvm=None):
    b, s, h, d = q.shape
    bq, bk, cb = layout.bq, layout.bk, layout.cb
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    nq = s // bq
    L = layout.lut_k.shape[-1]
    fq, fk = bq // cb, bk // cb
    use_mask = kvm is not None

    in_specs = [
        pl.BlockSpec((1, 1, 1, fq, fk),
                     lambda bi, hi, qi, t, lut, cnt:
                     (hi, qi, lut[hi, qi, t], 0, 0)),
        pl.BlockSpec((1, 1, bq, d),
                     lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, qi, t, lut, cnt:
                     (bi, hi, lut[hi, qi, t], 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, qi, t, lut, cnt:
                     (bi, hi, lut[hi, qi, t], 0)),
    ]
    operands = [layout.lut_k, layout.cnt_k, layout.fine_tiles, qt, kt, vt]
    if use_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda bi, hi, qi, t, lut, cnt:
            (bi, 0, lut[hi, qi, t])))
        operands.append(kvm[:, None, :])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nq, L),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1),
                         lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_fwd_kernel, scale=scale, cb=cb, block_q=bq,
                               block_k=bk, causal=causal, use_mask=use_mask)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(*operands)
    return out.transpose(0, 2, 1, 3), (qt, kt, vt, out, lse)


def _sparse_bwd(layout: _CompiledLayout, causal, scale, res, g, kvm=None):
    qt, kt, vt, out, lse = res
    b, h, s, d = qt.shape
    bq, bk, cb = layout.bq, layout.bk, layout.cb
    dot = g.transpose(0, 2, 1, 3)
    delta = jnp.sum(dot.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    nq, nk = s // bq, s // bk
    fq, fk = bq // cb, bk // cb
    L = layout.lut_k.shape[-1]
    Lq = layout.lut_q.shape[-1]
    use_mask = kvm is not None

    dq_in_specs = [
        pl.BlockSpec((1, 1, 1, fq, fk),
                     lambda bi, hi, qi, t, lut, cnt:
                     (hi, qi, lut[hi, qi, t], 0, 0)),
        pl.BlockSpec((1, 1, bq, d),
                     lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, qi, t, lut, cnt:
                     (bi, hi, lut[hi, qi, t], 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, qi, t, lut, cnt:
                     (bi, hi, lut[hi, qi, t], 0)),
        pl.BlockSpec((1, 1, bq, d),
                     lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, 1),
                     lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, 1),
                     lambda bi, hi, qi, t, lut, cnt: (bi, hi, qi, 0)),
    ]
    dq_operands = [layout.lut_k, layout.cnt_k, layout.fine_tiles, qt, kt,
                   vt, dot, lse, delta]
    if use_mask:
        dq_in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda bi, hi, qi, t, lut, cnt:
            (bi, 0, lut[hi, qi, t])))
        dq_operands.append(kvm[:, None, :])

    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nq, L),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi, t, lut, cnt:
                               (bi, hi, qi, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, cb=cb, block_q=bq,
                          block_k=bk, causal=causal, use_mask=use_mask),
        grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
        interpret=interpret_mode(),
    )(*dq_operands)

    dkv_in_specs = [
        pl.BlockSpec((1, 1, 1, fq, fk),
                     lambda bi, hi, ki, t, lut, cnt:
                     (hi, lut[hi, ki, t], ki, 0, 0)),
        pl.BlockSpec((1, 1, bq, d),
                     lambda bi, hi, ki, t, lut, cnt:
                     (bi, hi, lut[hi, ki, t], 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, ki, t, lut, cnt: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, ki, t, lut, cnt: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, bq, d),
                     lambda bi, hi, ki, t, lut, cnt:
                     (bi, hi, lut[hi, ki, t], 0)),
        pl.BlockSpec((1, 1, bq, 1),
                     lambda bi, hi, ki, t, lut, cnt:
                     (bi, hi, lut[hi, ki, t], 0)),
        pl.BlockSpec((1, 1, bq, 1),
                     lambda bi, hi, ki, t, lut, cnt:
                     (bi, hi, lut[hi, ki, t], 0)),
    ]
    dkv_operands = [layout.lut_q, layout.cnt_q, layout.fine_tiles, qt, kt,
                    vt, dot, lse, delta]
    if use_mask:
        dkv_in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda bi, hi, ki, t, lut, cnt: (bi, 0, ki)))
        dkv_operands.append(kvm[:, None, :])

    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nk, Lq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, ki, t, lut, cnt: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, ki, t, lut, cnt: (bi, hi, ki, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, cb=cb, block_q=bq,
                          block_k=bk, causal=causal, use_mask=use_mask),
        grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), kt.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), vt.dtype),
        ],
        interpret=interpret_mode(),
    )(*dkv_operands)

    tr = lambda x: x.transpose(0, 2, 1, 3)
    return tr(dq), tr(dk), tr(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _sparse_attn(q, k, v, layout, causal, scale):
    out, _ = _sparse_fwd(q, k, v, layout, causal, scale)
    return out


def _sparse_attn_fwd(q, k, v, layout, causal, scale):
    return _sparse_fwd(q, k, v, layout, causal, scale)


def _sparse_attn_bwd(layout, causal, scale, res, g):
    return _sparse_bwd(layout, causal, scale, res, g)


_sparse_attn.defvjp(_sparse_attn_fwd, _sparse_attn_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _sparse_attn_masked(q, k, v, kvm, layout, causal, scale):
    out, _ = _sparse_fwd(q, k, v, layout, causal, scale, kvm=kvm)
    return out


def _sparse_attn_masked_fwd(q, k, v, kvm, layout, causal, scale):
    out, res = _sparse_fwd(q, k, v, layout, causal, scale, kvm=kvm)
    return out, (res, kvm)


def _sparse_attn_masked_bwd(layout, causal, scale, res_kvm, g):
    res, kvm = res_kvm
    dq, dk, dv = _sparse_bwd(layout, causal, scale, res, g, kvm=kvm)
    return dq, dk, dv, jnp.zeros_like(kvm)


_sparse_attn_masked.defvjp(_sparse_attn_masked_fwd, _sparse_attn_masked_bwd)


def sparse_attention(q, k, v, sparsity_config: SparsityConfig,
                     sm_scale: Optional[float] = None,
                     causal: Optional[bool] = None,
                     key_padding_mask=None):
    """Block-sparse attention. q, k, v: [B, S, H, D] -> [B, S, H, D].

    ``causal=None`` derives causality from ``sparsity_config.attention``;
    pass ``causal=True`` explicitly for autoregressive use (exact
    elementwise masking, and the layout is tril-ified so dead tiles are
    skipped). Compiled layouts (LUTs) are cached per (seq_len, causal) on
    the config, mirroring the reference's master-layout buffering
    (sparse_self_attention.py:57).

    ``key_padding_mask``: optional [B, S] (1 = attend, 0 = masked key) —
    the reference ``SparseSelfAttention.forward`` key_padding_mask, used by
    the BERT family after ``SparseAttentionUtils.pad_to_block_size``.
    Masked keys drop out elementwise inside the kernel tiles; a query whose
    visible keys are ALL masked (a pure-padding row) outputs zeros.
    """
    b, s, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if causal is None:
        causal = getattr(sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
    cache = getattr(sparsity_config, "_layout_cache", None)
    if cache is None:
        cache = {}
        sparsity_config._layout_cache = cache
    key = (s, bool(causal))
    if key not in cache:
        fine = np.asarray(sparsity_config.make_layout(s), np.int64)
        if fine.shape[0] != h:
            raise ValueError(f"sparsity layout has {fine.shape[0]} heads, "
                             f"tensors have {h}")
        cb = sparsity_config.block
        bq = _kernel_block(s, cb)
        cache[key] = _CompiledLayout(fine, cb, bq, bq, causal)
    layout = cache[key]
    if key_padding_mask is not None:
        kvm = jnp.asarray(key_padding_mask).astype(jnp.float32)
        if kvm.shape != (b, s):
            raise ValueError(
                f"key_padding_mask must be [B, S] = {(b, s)}, "
                f"got {kvm.shape}")
        return _sparse_attn_masked(q, k, v, kvm, layout, causal, scale)
    return _sparse_attn(q, k, v, layout, causal, scale)
