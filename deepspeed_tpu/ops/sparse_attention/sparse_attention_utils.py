"""Model-integration helpers for block-sparse attention.

Reference: ``deepspeed/ops/sparse_attention/sparse_attention_utils.py:225``
(``SparseAttentionUtils``) — pad inputs to the sparsity block size, patch
HF BERT/RoBERTa self-attention with ``BertSparseSelfAttention``, extend
position embeddings for longer sequences, unpad outputs.

TPU shape: "patching a module" is a config choice here — the GPT family
takes ``attention_impl="sparse"`` + a SparsityConfig directly — so what
remains are the input-geometry helpers (sequences must be whole blocks for
the LUT kernels) and the embedding extension for beyond-pretraining
lengths."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def pad_to_block_size(block: int, input_ids, *, attention_mask=None,
                          token_type_ids=None, pad_token_id: int = 0):
        """Right-pad [B, S] inputs so S is a whole number of sparsity
        blocks (reference pad_to_block_size:225). Returns
        (pad_len, input_ids, attention_mask, token_type_ids); the mask
        zeros the padding so attention ignores it."""
        b, s = input_ids.shape
        pad_len = (-s) % block
        if pad_len == 0:
            if attention_mask is None:
                attention_mask = jnp.ones((b, s), jnp.int32)
            return 0, input_ids, attention_mask, token_type_ids
        input_ids = jnp.pad(input_ids, ((0, 0), (0, pad_len)),
                            constant_values=pad_token_id)
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.int32)
        attention_mask = jnp.pad(attention_mask, ((0, 0), (0, pad_len)))
        if token_type_ids is not None:
            token_type_ids = jnp.pad(token_type_ids, ((0, 0), (0, pad_len)))
        return pad_len, input_ids, attention_mask, token_type_ids

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Drop the padding rows again (reference unpad_sequence_output)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]

    @staticmethod
    def extend_position_embedding(wpe: jnp.ndarray, max_position: int):
        """Tile the pretrained position table out to ``max_position``
        (reference extend_position_embedding: repeats the learned table so
        a 512-pos BERT can serve 2048-token sparse attention)."""
        cur = wpe.shape[0]
        if max_position <= cur:
            return wpe[:max_position]
        reps = -(-max_position // cur)
        return jnp.tile(wpe, (reps, 1))[:max_position]

    @staticmethod
    def sparse_gpt_config(cfg, sparsity_config) -> Any:
        """The module-patch analogue (reference replace_model_self_attention
        + update_config): the same model runs block-sparse by config — no
        module surgery needed in a functional framework."""
        import dataclasses
        return dataclasses.replace(cfg, attention_impl="sparse",
                                   sparse_attention=sparsity_config)
