"""Native op builder: JIT-compiles csrc/ into a shared library and loads it
via ctypes.

Reference analogue: ``op_builder/builder.py:107-720`` — the OpBuilder ABC
with JIT compilation, compatibility probing (``is_compatible``), cpu-arch
flag selection, and a build cache. Differences: no torch cpp_extension —
plain g++ -shared -fPIC with ctypes bindings (the build contract allows
ctypes/cffi/CPython API, not pybind11), cached per source-hash under
~/.cache/deepspeed_tpu.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

from ..utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CSRC = os.path.join(_REPO_ROOT, "csrc")
_CACHE_DIR = os.environ.get(
    "DS_BUILD_DIR", os.path.join(os.path.expanduser("~"), ".cache",
                                 "deepspeed_tpu"))

_lib = None
_build_error: Optional[str] = None


def _cpu_arch_flags():
    """-march flags gated on actual CPU support (reference
    builder.py cpu_arch / simd_width probing)."""
    flags = ["-O3", "-fopenmp", "-std=c++17"]
    try:
        cpuinfo = open("/proc/cpuinfo").read()
        if "avx2" in cpuinfo:
            flags += ["-mavx2", "-mfma"]
        if "avx512f" in cpuinfo:
            flags += ["-mavx512f"]
    except OSError:
        pass
    return flags


def _sources():
    return sorted(
        os.path.join(_CSRC, f) for f in os.listdir(_CSRC)
        if f.endswith(".cpp"))


def build_native_lib(verbose: bool = False) -> Optional[str]:
    """Compile csrc/*.cpp -> cached .so; returns path or None on failure."""
    global _build_error
    srcs = _sources()
    if not srcs:
        _build_error = "no csrc sources found"
        return None
    h = hashlib.sha256()
    for s in srcs:
        h.update(open(s, "rb").read())
    tag = h.hexdigest()[:16]
    os.makedirs(_CACHE_DIR, exist_ok=True)
    out = os.path.join(_CACHE_DIR, f"libds_native_{tag}.so")
    if os.path.exists(out):
        return out
    cmd = ["g++", "-shared", "-fPIC", *_cpu_arch_flags(), *srcs, "-o",
           out + ".tmp", "-lpthread"]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        _build_error = f"compiler launch failed: {e}"
        return None
    if res.returncode != 0:
        _build_error = res.stderr[-2000:]
        if verbose:
            logger.warning(f"native build failed:\n{_build_error}")
        return None
    os.replace(out + ".tmp", out)
    logger.info(f"built native lib: {out}")
    return out


def get_native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    path = build_native_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    # ---- signatures ----
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    i64 = ctypes.c_int64
    lib.ds_adam_step.argtypes = [f32p, f32p, f32p, f32p, i64,
                                 ctypes.c_float, ctypes.c_float,
                                 ctypes.c_float, ctypes.c_float,
                                 ctypes.c_float, ctypes.c_int, i64]
    lib.ds_adam_step.restype = None
    lib.ds_adam_step_bf16.argtypes = [f32p, u16p, f32p, f32p, f32p, i64,
                                      ctypes.c_float, ctypes.c_float,
                                      ctypes.c_float, ctypes.c_float,
                                      ctypes.c_float, ctypes.c_int, i64]
    lib.ds_adam_step_bf16.restype = None
    lib.ds_adagrad_step.argtypes = [f32p, f32p, f32p, i64, ctypes.c_float,
                                    ctypes.c_float, ctypes.c_float]
    lib.ds_adagrad_step.restype = None
    lib.aio_handle_new.argtypes = [i64, ctypes.c_int, ctypes.c_int]
    lib.aio_handle_new.restype = ctypes.c_void_p
    lib.aio_handle_free.argtypes = [ctypes.c_void_p]
    lib.aio_handle_free.restype = None
    lib.aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.aio_open.restype = ctypes.c_int
    lib.aio_close.argtypes = [ctypes.c_int]
    lib.aio_close.restype = None
    for fn in ("aio_pread", "aio_pwrite"):
        g = getattr(lib, fn)
        g.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, i64, i64]
        g.restype = i64
    lib.aio_wait.argtypes = [ctypes.c_void_p]
    lib.aio_wait.restype = i64
    for fn in ("aio_sync_pread", "aio_sync_pwrite"):
        g = getattr(lib, fn)
        g.argtypes = [ctypes.c_int, ctypes.c_void_p, i64, i64]
        g.restype = i64
    _lib = lib
    return _lib


def is_compatible() -> bool:
    return get_native_lib() is not None


def build_report() -> str:
    """ds_report-style compatibility line (reference bin/ds_report)."""
    lib = get_native_lib()
    if lib is not None:
        return f"native ops ............. OK ({_CACHE_DIR})"
    return f"native ops ............. UNAVAILABLE ({_build_error})"


class _NativeOpBuilder:
    """Per-op view over the single native library (reference: one OpBuilder
    subclass per op, op_builder/cpu_adam.py:8, async_io.py:10). All native
    ops here live in one .so; compat is shared, the symbol check is per-op."""

    def __init__(self, name: str, symbols):
        self.name = name
        self.symbols = symbols

    def is_compatible(self) -> bool:
        lib = get_native_lib()
        return lib is not None and all(hasattr(lib, s) for s in self.symbols)

    def load(self):
        lib = get_native_lib()
        if lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        return lib


class _PallasOpBuilder:
    """Device-kernel 'builder': Pallas kernels need no compilation step
    (XLA jits them); compat = importable + a TPU backend or interpret mode."""

    def __init__(self, name: str, module: str):
        self.name = name
        self.module = module

    def is_compatible(self) -> bool:
        try:
            __import__(self.module, fromlist=["_"])
            return True
        except Exception:
            return False

    def load(self):
        return __import__(self.module, fromlist=["_"])


def available_builders():
    """Name -> builder map for ds_report (reference op_builder.ALL_OPS)."""
    pk = "deepspeed_tpu.ops"
    return {
        "cpu_adam": _NativeOpBuilder("cpu_adam",
                                     ["ds_adam_step", "ds_adam_step_bf16"]),
        "cpu_adagrad": _NativeOpBuilder("cpu_adagrad", ["ds_adagrad_step"]),
        "async_io": _NativeOpBuilder("async_io",
                                     ["aio_handle_new", "aio_pread",
                                      "aio_pwrite", "aio_wait"]),
        "flash_attn": _PallasOpBuilder("flash_attn",
                                       f"{pk}.pallas.flash_attention"),
        "fused_layer_norm": _PallasOpBuilder("fused_layer_norm",
                                             f"{pk}.pallas.layer_norm"),
        "fused_softmax": _PallasOpBuilder("fused_softmax",
                                          f"{pk}.pallas.softmax"),
        "fused_gelu": _PallasOpBuilder("fused_gelu", f"{pk}.pallas.gelu"),
        "sparse_attn": _PallasOpBuilder(
            "sparse_attn", f"{pk}.sparse_attention.sparse_self_attention"),
        "quantizer": _PallasOpBuilder("quantizer", f"{pk}.quantizer"),
    }
