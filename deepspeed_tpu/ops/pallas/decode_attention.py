"""Fused KV-cache decode attention: read only the filled prefix.

Reference analogue: the ``softmax_context`` inference kernel
(``csrc/transformer/inference/csrc/softmax.cu``) — single-token attention
over the KV cache. The plain XLA decode path does O(max_seq_len) work per
token regardless of fill (masked einsum over the whole cache).

This kernel makes both COMPUTE and HBM TRAFFIC O(cache_len): the cache
stays in HBM and the kernel drives its own double-buffered DMA pipeline
over a ``fori_loop`` whose trip count is the number of LIVE kv blocks (a
scalar-prefetch operand). Dead blocks are never fetched — the
splash-attention pattern applied to a dynamic prefix length. (The previous
revision walked a grid over all of S with a clamped index_map; Mosaic
re-issued the clamped block's DMA every dead step, so HBM traffic stayed
O(max_seq_len) and XLA won.)

Layout notes, the part that makes Mosaic happy AND fast:
  * The cache rides FLATTENED as [b, S, h*d] — a free reshape of the
    native [b, S, h, d] cache. The rank-4 layout tiles (h, d) and
    lane-pads d (64 -> 128), which both doubles the DMA bytes and makes
    dynamic sub-slices unaligned; the flat layout's (S, h*d) tiling is
    exactly aligned, so a [bk, h*d] block is one contiguous DMA.
  * Per-head dots become ONE MXU matmul against a block-diagonal query
    matrix qmat [h*d, hp] (qmat[g*d + j, g] = q[g, j]):
    s = k_flat @ qmat. The combine p^T @ v_flat yields [hp, h*d] whose
    row g holds every head's segment weighted by head g's probabilities;
    the wrapper slices the block diagonal — 16x more output elements than
    needed, but the arrays are tiny and it keeps the hot loop on the MXU.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode

# jax >= 0.5 renames TPUMemorySpace -> MemorySpace (and ANY -> HBM for
# refs the kernel DMAs out of itself); accept either so the kernel runs
# against both toolchains
if hasattr(pltpu, "MemorySpace"):
    _MEM_HBM = pltpu.MemorySpace.HBM
else:
    _MEM_HBM = pltpu.TPUMemorySpace.ANY

NEG_INF = float(np.finfo(np.float32).min)

# Widest speculative-verify query width (k+1 draft positions) the kernels
# take in-kernel. The qmat lane dim is s*hp, so wider shapes would start
# eating MXU lanes for masked-out work; past this the wrappers fall back
# to the gather/einsum path (prefill always does — s there is prompt-len).
MAX_SPEC_S = 8


def _spec_live_mask(pos, fill, s, hp, shape):
    """[bk, s*hp] causal liveness: query column-group i (lanes i*hp ..
    (i+1)*hp) sits at absolute position ``fill - s + i``, so key position
    ``pos`` is visible iff ``pos < fill - (s-1) + i``. For s == 1 this is
    the plain filled-prefix mask (kept on its scalar form so the
    single-token hot path's codegen is untouched)."""
    if s == 1:
        return pos < fill
    qidx = jax.lax.broadcasted_iota(jnp.int32, shape, 1) // hp
    return pos < fill - (s - 1) + qidx


def _decode_kernel(meta_ref, qmat_ref, *refs, scale, block_k, b, hp, hd,
                   quantized=False, s=1):
    """Single program. k_hbm/v_hbm: full [b, S, h*d] refs in HBM;
    k_buf/v_buf: [2, b, block_k, h*d] VMEM slots — ALL batch rows ride one
    (strided) DMA per block, so the DMA count is O(live blocks), not
    O(b * live blocks). Online softmax state rides the loop carry; the
    per-batch dots unroll statically (b is small at decode time).

    meta_ref: [1 + b] scalars — [0] is the live block count (max over
    rows), [1 + bi] row bi's filled prefix length. Per-row lengths are what
    continuous-batching serving needs: every slot sits at its own fill, so
    the mask is per-row while the DMA window is sized by the deepest slot.

    ``quantized``: the cache rides int8 with per-position f32 dequant
    multipliers ks_hbm/vs_hbm [b, S] — int8 blocks are DMA-streamed
    (half/quarter the HBM bytes) and the scale-multiply happens here in
    VMEM right before the MXU dot.

    ``s``: static query positions per lane (the k+1 speculative-verify
    shape). The block-diagonal qmat widens to [h*d, s*hp] — column group
    i is query position i's block-diagonal matrix — so the s-position
    scores still come out of ONE MXU matmul; the causal mask staggers per
    column group (:func:`_spec_live_mask`) and the online-softmax carries
    widen to [b, s*hp]. The DMA window is unchanged: int8 dequant stays
    fused in VMEM, so the spec path never materializes an f32 cache."""
    if quantized:
        (k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         k_sem, v_sem, ks_sem, vs_sem) = refs
    else:
        k_hbm, v_hbm, o_ref, k_buf, v_buf, k_sem, v_sem = refs
    nb = meta_ref[0]       # live kv blocks (max over batch rows)

    def block_copies(i, slot):
        win = pl.ds(i * block_k, block_k)
        out = [
            pltpu.make_async_copy(k_hbm.at[:, win], k_buf.at[slot],
                                  k_sem.at[slot]),
            pltpu.make_async_copy(v_hbm.at[:, win], v_buf.at[slot],
                                  v_sem.at[slot]),
        ]
        if quantized:
            out.append(pltpu.make_async_copy(
                ks_hbm.at[:, win], ks_buf.at[slot], ks_sem.at[slot]))
            out.append(pltpu.make_async_copy(
                vs_hbm.at[:, win], vs_buf.at[slot], vs_sem.at[slot]))
        return out

    # prologue: stage block 0 into slot 0
    for c in block_copies(0, 0):
        c.start()

    def body(i, carry):
        m_prev, l_prev, acc = carry                # [b,hp] [b,hp] [b,hp,hd]
        slot = jax.lax.rem(i, 2)
        nxt = i + 1

        @pl.when(nxt < nb)
        def _prefetch():
            ns = jax.lax.rem(nxt, 2)
            for c in block_copies(nxt, ns):
                c.start()

        for c in block_copies(i, slot):
            c.wait()
        pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, s * hp), 0)
        ms, ls, accs = [], [], []
        for bi in range(b):                        # static unroll
            live = _spec_live_mask(pos, meta_ref[1 + bi], s, hp,
                                   (block_k, s * hp))
            kbk = k_buf[slot, bi].astype(jnp.float32)   # [bk, h*d]
            vbk = v_buf[slot, bi].astype(jnp.float32)
            if quantized:
                kbk = kbk * ks_buf[slot, bi][:, None]
                vbk = vbk * vs_buf[slot, bi][:, None]
            qmat = qmat_ref[bi].astype(jnp.float32)     # [h*d, s*hp]
            sc = jax.lax.dot(kbk, qmat,
                             preferred_element_type=jnp.float32) * scale
            sc = jnp.where(live, sc, NEG_INF)
            m_new = jnp.maximum(m_prev[bi], jnp.max(sc, axis=0))
            p = jnp.exp(sc - m_new[None, :])
            corr = jnp.exp(m_prev[bi] - m_new)
            l_new = l_prev[bi] * corr + jnp.sum(p, axis=0)
            # p^T @ v: [hp, h*d]; row g = every segment under head-g weights
            pv = jax.lax.dot_general(p, vbk, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ms.append(m_new)
            ls.append(l_new)
            accs.append(acc[bi] * corr[:, None] + pv)
        return (jnp.stack(ms), jnp.stack(ls), jnp.stack(accs))

    m0 = jnp.full((b, s * hp), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s * hp), jnp.float32)
    a0 = jnp.zeros((b, s * hp, hd), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, :, None]).astype(o_ref.dtype)


def _pick_block(s: int, want: int = 256) -> Optional[int]:
    cand = want
    while cand >= 128:
        if s % cand == 0:
            return cand
        cand //= 2
    return s if s <= 128 and s % 8 == 0 else None


_VMEM_BUDGET = 8 * 1024 * 1024   # staging window budget (2 slots x k+v)


def _choose_block(b: int, S: int, h: int, d: int, itemsize: int,
                  block_k: Optional[int] = None) -> Optional[int]:
    """kv block size for the DMA window, or None when the kernel can't run
    (S not block-decomposable, h*d lane-unaligned handled by caller, or the
    window would blow the VMEM arena even at the smallest block). Every
    candidate must divide S — a non-divisor would silently drop the cache
    tail (nb is clipped to S // bk)."""
    if block_k is not None:
        if S % block_k != 0:
            raise ValueError(
                f"block_k={block_k} must divide the cache length S={S}")
        bk = block_k
    else:
        bk = _pick_block(S)
    if bk is None:
        return None
    while bk > 128 and 4 * b * bk * h * d * itemsize > _VMEM_BUDGET \
            and S % (bk // 2) == 0:
        bk //= 2
    if S % bk != 0 or 4 * b * bk * h * d * itemsize > _VMEM_BUDGET:
        return None
    return bk


def pallas_decode_supported(b: int, S: int, h: int, d: int, dtype,
                            s: int = 1) -> bool:
    """Callers choosing a cache LAYOUT (models/gpt.py flat cache) must agree
    with the kernel's own feasibility test — a flat cache whose every decode
    falls back to the XLA path would pay a full-cache relayout per token.
    ``s``: query positions per lane (1 = plain decode, 2..MAX_SPEC_S = the
    speculative-verify shape)."""
    if not 1 <= s <= MAX_SPEC_S:
        return False
    if (h * d) % 128 != 0:
        return False
    return _choose_block(b, S, h, d, jnp.dtype(dtype).itemsize) is not None


def _spec_qmat(q: jnp.ndarray, hp: int) -> jnp.ndarray:
    """Block-diagonal query matrix for s query positions:
    qmat[b, g*d + j, i*hp + g] = q[b, i, g, j] — column group i holds
    position i's block-diagonal so all s*h per-head dots are one MXU
    matmul against the flat [bk, h*d] cache block."""
    b, s, h, d = q.shape
    eye = jnp.eye(h, hp, dtype=q.dtype)                     # [h, hp]
    return jnp.einsum("bshd,hg->bhdsg", q, eye).reshape(b, h * d, s * hp)


def _slice_block_diagonal(out: jnp.ndarray, s: int, h: int,
                          d: int) -> jnp.ndarray:
    """Invert the block-diagonal packing: kernel output row i*hp + g holds
    every head's segment weighted under (query i, head g); the real output
    is segment g of that row -> [b, s, h, d]."""
    b, sp, hd = out.shape
    hp = sp // s
    out = out.reshape(b, s, hp, hd)[:, :, :h].reshape(b, s, h, h, d)
    out = jnp.diagonal(out, axis1=2, axis2=3)               # [b, s, d, h]
    return out.transpose(0, 1, 3, 2)                        # [b, s, h, d]


def decode_attention(q: jnp.ndarray, cached_key: jnp.ndarray,
                     cached_value: jnp.ndarray, cache_len,
                     scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     k_scale: Optional[jnp.ndarray] = None,
                     v_scale: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """q: [b, 1, h, d]. cached_key/value: PREFERABLY the flat [b, S, h*d]
    cache layout — rank-4 [b, S, h, d] caches are accepted but XLA
    lane-pads their d dim (64 -> 128), so every call pays a full-cache
    relayout copy; keep the cache flat (models/gpt.py does when decode_impl
    resolves to pallas). cache_len: count of valid cache positions
    (including this token, already written) — a scalar when every row sits
    at the same fill (single-stream generate), or a [b] int32 vector of
    per-row fills (slotted continuous-batching decode, serving/engine.py).
    Masked-lane entries may sit past the cache extent (the serving
    engine's retired-lane sentinel is ``max_seq_len``); they are clamped
    to S here so the DMA window / mask math stays in range — the lane's
    output is garbage the caller discards, never an OOB access.
    ``k_scale``/``v_scale`` [b, S] f32 mark an int8 cache
    (kv_cache_dtype="int8"): per-position dequant multipliers, applied in
    VMEM on the Pallas path and before the masked einsum on the fallback.
    ``s_q`` in 2..MAX_SPEC_S is the speculative-verify shape and stays on
    the kernel (s-position qmat); wider s_q (prefill) falls back.
    Returns [b, s_q, h, d] (so [b, 1, h, d] for plain decode)."""
    b, s_q, h, d = q.shape
    S = cached_key.shape[1]
    cache_len = jnp.minimum(jnp.asarray(cache_len, jnp.int32), S)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bk = _choose_block(b, S, h, d, jnp.dtype(cached_key.dtype).itemsize,
                       block_k)
    flat = cached_key.ndim == 3
    quantized = k_scale is not None
    if not 1 <= s_q <= MAX_SPEC_S or bk is None or (h * d) % 128 != 0:
        if quantized:
            from ..quantizer import dequantize_kv
            sk = k_scale[..., None] if flat else k_scale[..., None, None]
            sv = v_scale[..., None] if flat else v_scale[..., None, None]
            cached_key = dequantize_kv(cached_key, sk, q.dtype)
            cached_value = dequantize_kv(cached_value, sv, q.dtype)
        if flat:
            cached_key = cached_key.reshape(b, S, h, d)
            cached_value = cached_value.reshape(b, S, h, d)
        return _xla_decode(q, cached_key, cached_value, cache_len, scale)

    hp = -(-h // 8) * 8
    hd = h * d
    # block-diagonal query: qmat[g*d + j, i*hp + g] = q[i, g, j]
    qmat = _spec_qmat(q, hp)                                # [b, hd, s*hp]

    clen = jnp.broadcast_to(jnp.asarray(cache_len, jnp.int32), (b,))
    # DMA window sized by the deepest row; shallower rows mask in-kernel
    nb = jnp.clip((jnp.max(clen) + bk - 1) // bk, 1, S // bk)
    meta = jnp.concatenate([nb[None], clen])

    if flat:
        kf, vf = cached_key, cached_value
    else:
        kf = cached_key.reshape(b, S, hd)
        vf = cached_value.reshape(b, S, hd)

    kernel = functools.partial(_decode_kernel, scale=scale, block_k=bk,
                               b=b, hp=hp, hd=hd, quantized=quantized,
                               s=s_q)
    in_specs = [
        pl.BlockSpec((b, hd, s_q * hp), lambda g, meta: (0, 0, 0)),
        # the cache never enters VMEM wholesale: the kernel DMAs only
        # live blocks out of HBM
        pl.BlockSpec(memory_space=_MEM_HBM),
        pl.BlockSpec(memory_space=_MEM_HBM),
    ]
    scratch = [
        pltpu.VMEM((2, b, bk, hd), cached_key.dtype),
        pltpu.VMEM((2, b, bk, hd), cached_value.dtype),
    ]
    sems = [pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,))]
    operands = [meta, qmat, kf, vf]
    if quantized:
        in_specs += [pl.BlockSpec(memory_space=_MEM_HBM),
                     pl.BlockSpec(memory_space=_MEM_HBM)]
        scratch += [pltpu.VMEM((2, b, bk), jnp.float32),
                    pltpu.VMEM((2, b, bk), jnp.float32)]
        sems += [pltpu.SemaphoreType.DMA((2,)),
                 pltpu.SemaphoreType.DMA((2,))]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, s_q * hp, hd), lambda g, meta: (0, 0, 0)),
        scratch_shapes=scratch + sems,
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s_q * hp, hd), q.dtype),
        interpret=interpret_mode(),
    )(*operands)
    # block diagonal: (query i, head g)'s output is row i*hp+g, segment g
    return _slice_block_diagonal(out, s_q, h, d)


# --------------------------------------------------------------------------
# Paged decode attention: gather K/V through a per-row block table
# --------------------------------------------------------------------------

def _paged_decode_kernel(meta_ref, bt_ref, qmat_ref, *refs, scale, b, hp,
                         hd, bs, nb_total, quantized=False, s=1):
    """Paged variant of :func:`_decode_kernel`. k_hbm/v_hbm are the FULL
    block pools [nb_total, bs, h*d] in HBM; each fori step DMAs one
    block PER ROW (rows no longer share a contiguous window — that is
    the price of paging, paid as b strided copies per step instead of
    one), double-buffered through [2, b, bs, h*d] VMEM with a (2, b)
    semaphore grid. meta_ref: [1 + b] — [0] the live block count (max
    over rows), [1 + bi] row bi's filled prefix. bt_ref: [b, T] block
    tables (scalar-prefetch, so the DMA source indices are host-known
    ints at issue time); entries past a row's reservation are clamped
    into the pool and masked dead by the fill. ``quantized``: int8 pools
    with per-position f32 dequant multiplier pools ks_hbm/vs_hbm
    [nb_total, bs], DMA'd per-(row, block) alongside the payload and
    applied in VMEM. ``s``: static query positions per lane (the
    speculative-verify shape — same widened qmat / staggered mask as
    :func:`_decode_kernel`)."""
    if quantized:
        (k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         k_sem, v_sem, ks_sem, vs_sem) = refs
    else:
        k_hbm, v_hbm, o_ref, k_buf, v_buf, k_sem, v_sem = refs
    nb = meta_ref[0]

    def row_copies(i, slot, bi):
        blk = jnp.minimum(bt_ref[bi, i], nb_total - 1)
        out = [
            pltpu.make_async_copy(k_hbm.at[blk], k_buf.at[slot, bi],
                                  k_sem.at[slot, bi]),
            pltpu.make_async_copy(v_hbm.at[blk], v_buf.at[slot, bi],
                                  v_sem.at[slot, bi]),
        ]
        if quantized:
            out.append(pltpu.make_async_copy(
                ks_hbm.at[blk], ks_buf.at[slot, bi], ks_sem.at[slot, bi]))
            out.append(pltpu.make_async_copy(
                vs_hbm.at[blk], vs_buf.at[slot, bi], vs_sem.at[slot, bi]))
        return out

    for bi in range(b):                    # prologue: stage block 0
        for c in row_copies(0, 0, bi):
            c.start()

    def body(i, carry):
        m_prev, l_prev, acc = carry            # [b,hp] [b,hp] [b,hp,hd]
        slot = jax.lax.rem(i, 2)
        nxt = i + 1

        @pl.when(nxt < nb)
        def _prefetch():
            ns = jax.lax.rem(nxt, 2)
            for bi in range(b):
                for c in row_copies(nxt, ns, bi):
                    c.start()

        pos = i * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, s * hp), 0)
        ms, ls, accs = [], [], []
        for bi in range(b):                    # static unroll
            for c in row_copies(i, slot, bi):
                c.wait()
            live = _spec_live_mask(pos, meta_ref[1 + bi], s, hp,
                                   (bs, s * hp))
            kbk = k_buf[slot, bi].astype(jnp.float32)     # [bs, h*d]
            vbk = v_buf[slot, bi].astype(jnp.float32)
            if quantized:
                kbk = kbk * ks_buf[slot, bi][:, None]
                vbk = vbk * vs_buf[slot, bi][:, None]
            qmat = qmat_ref[bi].astype(jnp.float32)       # [h*d, s*hp]
            sc = jax.lax.dot(kbk, qmat,
                             preferred_element_type=jnp.float32) * scale
            sc = jnp.where(live, sc, NEG_INF)
            m_new = jnp.maximum(m_prev[bi], jnp.max(sc, axis=0))
            p = jnp.exp(sc - m_new[None, :])
            corr = jnp.exp(m_prev[bi] - m_new)
            l_new = l_prev[bi] * corr + jnp.sum(p, axis=0)
            pv = jax.lax.dot_general(p, vbk, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ms.append(m_new)
            ls.append(l_new)
            accs.append(acc[bi] * corr[:, None] + pv)
        return (jnp.stack(ms), jnp.stack(ls), jnp.stack(accs))

    m0 = jnp.full((b, s * hp), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s * hp), jnp.float32)
    a0 = jnp.zeros((b, s * hp, hd), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, :, None]).astype(o_ref.dtype)


def paged_decode_supported(b: int, block_size: int, h: int, d: int,
                           dtype, s: int = 1) -> bool:
    """Kernel feasibility for the paged layout: lane-aligned h*d,
    sublane-aligned block_size (the DMA unit), the double-buffered
    staging window within the VMEM budget, and the query width s within
    the in-kernel speculative-verify range (1..MAX_SPEC_S)."""
    if not 1 <= s <= MAX_SPEC_S:
        return False
    if (h * d) % 128 != 0:
        return False
    itemsize = jnp.dtype(dtype).itemsize
    sublane = max(8, 32 // itemsize)
    if block_size % sublane != 0:
        return False
    return 4 * b * block_size * h * d * itemsize <= _VMEM_BUDGET


def paged_gather_kv(pool: jnp.ndarray,
                    block_tables: jnp.ndarray) -> jnp.ndarray:
    """Reference gather: pool [nb, bs, h*d] through block_tables [b, T]
    -> [b, T*bs, h*d]. Position p of row i reads flat pool index
    ``block_tables[i, p//bs]*bs + p%bs``; table entries past a row's
    reservation point at whatever block they name (zeros-padded tables
    read block 0) — those positions sit past the row's fill and are
    masked by the caller, so garbage is gathered but never attended."""
    nb, bs, hd = pool.shape
    b, T = block_tables.shape
    p = jnp.arange(T * bs)
    blk = jnp.take(block_tables, p // bs, axis=1)            # [b, S]
    flat = blk * bs + (p % bs)[None, :]
    return jnp.take(pool.reshape(nb * bs, hd), flat, axis=0,
                    mode="clip")


def paged_decode_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                           cache_len, scale: Optional[float] = None,
                           impl: str = "xla",
                           k_scale: Optional[jnp.ndarray] = None,
                           v_scale: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Decode attention over a PAGED cache. q: [b, s_q, h, d] (s_q > 1 is
    the speculative-verify shape); k_pool/v_pool: [nb, bs, h*d] block
    pools; block_tables: [b, T]; cache_len: valid positions per row
    (including this call's tokens, already written) — scalar or [b],
    sentinel entries past T*bs are clamped. ``k_scale``/``v_scale``
    [nb, bs] f32 mark int8 pools (per-position dequant multipliers).

    The reference path (CPU / unsupported shapes) gathers the pool
    through the table and calls the SAME masked einsum as the dense
    decode path — gathered values are bit-identical to the dense
    arena's rows, masked positions underflow to exact zeros, so greedy
    outputs are bit-identical to the dense oracle (the tier-1 parity
    gate). The Pallas path DMAs per-(row, block) through the table —
    compute and HBM traffic stay O(cache_len) per token."""
    b, s_q, h, d = q.shape
    nb, bs, hd = k_pool.shape
    T = block_tables.shape[1]
    S = T * bs
    clen = jnp.minimum(
        jnp.broadcast_to(jnp.asarray(cache_len, jnp.int32), (b,)), S)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    quantized = k_scale is not None
    if (impl == "pallas"
            and paged_decode_supported(b, bs, h, d, k_pool.dtype, s_q)):
        hp = -(-h // 8) * 8
        qmat = _spec_qmat(q, hp)                        # [b, hd, s*hp]
        nb_live = jnp.clip((jnp.max(clen) + bs - 1) // bs, 1, T)
        meta = jnp.concatenate([nb_live[None], clen])
        kernel = functools.partial(
            _paged_decode_kernel, scale=scale, b=b, hp=hp, hd=hd,
            bs=bs, nb_total=nb, quantized=quantized, s=s_q)
        in_specs = [
            pl.BlockSpec((b, hd, s_q * hp), lambda g, meta, bt: (0, 0, 0)),
            pl.BlockSpec(memory_space=_MEM_HBM),
            pl.BlockSpec(memory_space=_MEM_HBM),
        ]
        scratch = [
            pltpu.VMEM((2, b, bs, hd), k_pool.dtype),
            pltpu.VMEM((2, b, bs, hd), v_pool.dtype),
        ]
        sems = [pltpu.SemaphoreType.DMA((2, b)),
                pltpu.SemaphoreType.DMA((2, b))]
        operands = [meta, block_tables.astype(jnp.int32), qmat,
                    k_pool, v_pool]
        if quantized:
            in_specs += [pl.BlockSpec(memory_space=_MEM_HBM),
                         pl.BlockSpec(memory_space=_MEM_HBM)]
            scratch += [pltpu.VMEM((2, b, bs), jnp.float32),
                        pltpu.VMEM((2, b, bs), jnp.float32)]
            sems += [pltpu.SemaphoreType.DMA((2, b)),
                     pltpu.SemaphoreType.DMA((2, b))]
            operands += [k_scale.astype(jnp.float32),
                         v_scale.astype(jnp.float32)]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,          # meta + block tables
            grid=(1,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((b, s_q * hp, hd),
                                   lambda g, meta, bt: (0, 0, 0)),
            scratch_shapes=scratch + sems,
        )
        out = pl.pallas_call(
            kernel, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, s_q * hp, hd), q.dtype),
            interpret=interpret_mode(),
        )(*operands)
        return _slice_block_diagonal(out, s_q, h, d)
    kflat = paged_gather_kv(k_pool, block_tables)
    vflat = paged_gather_kv(v_pool, block_tables)
    if quantized:
        from ..quantizer import dequantize_kv
        ks = paged_gather_kv(k_scale[..., None].astype(jnp.float32),
                             block_tables)
        vs = paged_gather_kv(v_scale[..., None].astype(jnp.float32),
                             block_tables)
        kflat = dequantize_kv(kflat, ks, q.dtype)
        vflat = dequantize_kv(vflat, vs, q.dtype)
    kf = kflat.reshape(b, S, h, d)
    vf = vflat.reshape(b, S, h, d)
    return masked_cache_attention(q, kf, vf, clen - s_q, scale)


def masked_cache_attention(q, ck, cv, first_q_pos, scale, window=None):
    """The ONE masked-einsum cache attention (shared by the kernel's XLA
    fallback and the model's prefill/window paths, so the two can't drift):
    q [b, s, h, d] with query i at absolute position ``first_q_pos + i``,
    ck/cv [b, S, h, d]; each query sees keys at positions <= its own
    (within the trailing local ``window`` if given). ``first_q_pos``:
    scalar, or a [b] vector when each row decodes at its own fill (slotted
    serving)."""
    S = ck.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, ck).astype(jnp.float32) * scale
    key_pos = jnp.arange(S)[None, None, None, :]
    fq = jnp.asarray(first_q_pos)
    if fq.ndim == 1:                               # per-row fills: [b,1,s,1]
        q_pos = (fq[:, None] + jnp.arange(q.shape[1]))[:, None, :, None]
    else:
        q_pos = (fq + jnp.arange(q.shape[1]))[None, None, :, None]
    visible = key_pos <= q_pos
    if window is not None:
        visible = jnp.logical_and(visible, key_pos > q_pos - window)
    logits = jnp.where(visible, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)


def _xla_decode(q, ck, cv, cache_len, scale):
    """Masked-einsum fallback."""
    first_q = jnp.asarray(cache_len, jnp.int32) - q.shape[1]
    return masked_cache_attention(q, ck, cv, first_q, scale)
