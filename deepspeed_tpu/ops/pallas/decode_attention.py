"""Fused KV-cache decode attention: read only the filled prefix.

Reference analogue: the ``softmax_context`` inference kernel
(``csrc/transformer/inference/csrc/softmax.cu``) — single-token attention
over the KV cache. The plain XLA decode path does O(max_seq_len) work per
token regardless of fill (masked einsum over the whole cache); this kernel
makes the COMPUTE O(cache_len): the number of LIVE kv blocks rides in as a
scalar-prefetch operand, dead grid steps are predicated out, and their
index_map clamps to the last live block (the block-sparse kernel's LUT
trick applied to a dynamic prefix length).

Status: numerically verified on TPU v5e, but currently OPT-IN
(``GPTConfig.decode_impl="pallas"``) — the clamped index_map does not stop
Mosaic from re-issuing the clamped block's DMA on this toolchain, so HBM
traffic stays O(max_seq_len) and XLA's fused masked-einsum wins at these
sizes (84-124us vs 145-163us per token at b=4, S=2048, h=16 on v5e).
Making the win real needs a manual DMA pipeline over a dynamically-bounded
loop (splash-attention style) — tracked as follow-up work.

Layout: one query token, heads as the softmax row dimension —
q [b, h, d], cache [b, h, S, d], online softmax over kv blocks with
(m, l, acc) in VMEM scratch.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode

NEG_INF = float(np.finfo(np.float32).min)


def _decode_kernel(meta_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, block_k, h):
    kb = pl.program_id(1)
    nk_total = pl.num_programs(1)
    nb = meta_ref[0]       # number of live kv blocks
    clen = meta_ref[1]     # filled prefix length (includes this token)
    hp = m_scr.shape[0]    # head count padded to the sublane tile

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(kb < nb)
    def _compute():
        # cache blocks arrive in their NATIVE [bk, h, d] layout (no
        # host-side transpose — that would copy the whole cache per call);
        # per-head matvecs as broadcast-multiply-reduce (Mosaic has no
        # batched dot, and decode is DMA-bound — the VPU covers the FLOPs).
        # When h isn't a sublane multiple, k/v blocks are zero-padded to hp
        # in VMEM (q's pad rows are zero, so pad-head logits are 0 and the
        # junk lanes are sliced off by the wrapper).
        q = q_ref[0].astype(jnp.float32)          # [hp, d]
        kbk = k_ref[0].astype(jnp.float32)        # [bk, h, d]
        vbk = v_ref[0].astype(jnp.float32)
        if hp != h:
            widths = ((0, 0), (0, hp - h), (0, 0))
            kbk = jnp.pad(kbk, widths)
            vbk = jnp.pad(vbk, widths)
        s = jnp.sum(q[None, :, :] * kbk, axis=2) * scale      # [bk, hp]
        pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        s = jnp.where(pos < clen, s, NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=0))
        p = jnp.exp(s - m_new[None, :])
        corr = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * corr + jnp.sum(p, axis=0)
        acc_scr[...] = acc_scr[...] * corr[:, None] + jnp.sum(
            p[:, :, None] * vbk, axis=0)                      # [hp, d]

    @pl.when(kb == nk_total - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)


def _pick_block(s: int, want: int = 512) -> Optional[int]:
    cand = want
    while cand >= 128:
        if s % cand == 0:
            return cand
        cand //= 2
    return s if s <= 128 else None


def decode_attention(q: jnp.ndarray, cached_key: jnp.ndarray,
                     cached_value: jnp.ndarray, cache_len,
                     scale: Optional[float] = None,
                     block_k: Optional[int] = None) -> jnp.ndarray:
    """q: [b, 1, h, d]; cached_key/value: [b, S, h, d]; cache_len: scalar
    int32 count of valid cache positions (including this token, already
    written). Returns [b, 1, h, d]."""
    b, s_q, h, d = q.shape
    S = cached_key.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bk = block_k or _pick_block(S)
    if s_q != 1 or bk is None:
        return _xla_decode(q, cached_key, cached_value, cache_len, scale)

    # heads ride the sublane dim of q/out: pad to the TPU tile multiple.
    # The CACHE is consumed in its native [b, S, h, d] layout — h is its
    # sublane dim inside a block, so only q/out (tiny) ever get padded.
    hp = -(-h // 8) * 8
    qt = q[:, 0]                                   # [b, h, d]
    if hp != h:
        qt = jnp.pad(qt, ((0, 0), (0, hp - h), (0, 0)))

    nk = S // bk
    clen = jnp.asarray(cache_len, jnp.int32)
    nb = jnp.maximum((clen + bk - 1) // bk, 1)
    meta = jnp.stack([nb, clen])

    kernel = functools.partial(_decode_kernel, scale=scale, block_k=bk, h=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nk),
        in_specs=[
            pl.BlockSpec((1, hp, d), lambda bi, kb, meta: (bi, 0, 0)),
            # dead blocks clamp to the last live block: no fresh DMA
            pl.BlockSpec((1, bk, h, d),
                         lambda bi, kb, meta: (bi,
                                               jnp.minimum(kb, meta[0] - 1),
                                               0, 0)),
            pl.BlockSpec((1, bk, h, d),
                         lambda bi, kb, meta: (bi,
                                               jnp.minimum(kb, meta[0] - 1),
                                               0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hp, d), lambda bi, kb, meta: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hp,), jnp.float32),
            pltpu.VMEM((hp,), jnp.float32),
            pltpu.VMEM((hp, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hp, d), q.dtype),
        interpret=interpret_mode(),
    )(meta, qt, cached_key, cached_value)
    return out[:, :h].reshape(b, 1, h, d)


def _xla_decode(q, ck, cv, cache_len, scale):
    """Masked-einsum fallback (the previous default path)."""
    S = ck.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, ck).astype(jnp.float32) * scale
    visible = jnp.arange(S)[None, None, None, :] < cache_len
    logits = jnp.where(visible, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)
