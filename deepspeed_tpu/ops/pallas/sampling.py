"""Sort-free fused sampling epilogue: top-k/top-p filter + draw, one kernel.

Reference analogue: DeepSpeed's fused-softmax/sampling epilogues — the last
ops of every decode step run fused instead of as a separate XLA subgraph.
The composed path (serving/sampling.filter_logits + sample_tokens) pays a
``top_k`` partial sort plus a FULL [V] sort for nucleus filtering plus a
``categorical`` draw — three HBM round-trips over the logits per decode
step. This kernel keeps the [V] row in VMEM once and replaces both sorts
with monotonic-int bisections:

  * order keys: an IEEE-754 trick — ``bitcast(f32 -> i32)`` then reflect
    the negative range (``INT32_MAX - bits``, wraparound intended) gives a
    SIGNED int32 key that is strictly monotonic in the float order, so
    "the k-th largest logit" becomes an exact integer bisection (~32
    count-reductions over the VMEM-resident row), never a sort;
  * top-k: bisect for the largest key ``t`` with ``count(key >= t) >= k``
    — exactly ``jax.lax.top_k``'s k-th value, ties kept like the
    reference's ``logits < kth`` mask;
  * top-p: bisect on kept probability mass — find the largest key ``T``
    with ``mass(key > T) >= p``; the cut value is the smallest present
    key above ``T``. The kept SET matches the reference's minimal-
    covering-set semantics up to f32 summation rounding on the mass
    comparison (the reference cumsums post-division, we sum exps and
    compare against ``p * Z``);
  * draw: greedy is a first-index argmax (bit-identical to
    ``jnp.argmax``); temperature sampling is Gumbel-max over the filtered
    row (``argmax(x + g)`` with caller-supplied gumbel noise), the same
    distribution ``jax.random.categorical`` draws from.

Greedy outputs are bit-identical to the composed path — the megakernel
correctness contract. Temperature > 0 draws are distributionally
identical but consume a different rng stream than ``categorical``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (parity with sibling kernels)

from ._utils import interpret_mode

_NEG_CAP = -1e10                 # the reference filter's masked-logit value
_INT32_MAX = 2147483647          # python int: jnp arrays here would be
#                                  closure-captured consts the kernel rejects

# One f32 logits row (+ optional gumbel row) must sit in VMEM next to the
# kernel's reduction temporaries; cap the vocab well under the arena.
_MAX_VOCAB = 256 * 1024
_BISECT_ITERS = 33               # > log2(int32 key range): exact convergence


def _order_key(x: jnp.ndarray) -> jnp.ndarray:
    """Strictly monotonic f32 -> i32 order key. Non-negative floats keep
    their bit pattern; negative floats reflect (``INT32_MAX - bits``
    wraps for -0.0 by design) so every negative key < every non-negative
    key and ordering matches the float order. Finite inputs only."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    return jnp.where(b >= 0, b, _INT32_MAX - b)


def _mid(lo, hi):
    # overflow-safe floor((lo + hi) / 2) for int32 of either sign
    return (lo >> 1) + (hi >> 1) + (lo & hi & 1)


def _bisect_kth_key(key: jnp.ndarray, k: int) -> jnp.ndarray:
    """Exact k-th largest key: the largest t with count(key >= t) >= k.
    Invariant: count(>= lo) >= k, count(>= hi) < k."""
    lo = jnp.min(key)
    hi = jnp.max(key) + 1        # finite floats: max key < INT32_MAX

    def body(_, carry):
        lo, hi = carry
        mid = _mid(lo, hi)
        c = jnp.sum((key >= mid).astype(jnp.int32))
        take = c >= k
        return (jnp.where(take, mid, lo), jnp.where(take, hi, mid))

    lo, _ = jax.lax.fori_loop(0, _BISECT_ITERS, body, (lo, hi))
    return lo


def _bisect_top_p_key(key: jnp.ndarray, e: jnp.ndarray,
                      pz: jnp.ndarray) -> jnp.ndarray:
    """Nucleus cut key: with e = exp(x - max) and pz = top_p * sum(e),
    find the largest key T whose strictly-above mass still reaches pz,
    then cut at the smallest present key above T (the reference's minimal
    covering set: a token survives iff the mass strictly above it is
    < top_p). Invariant: mass(> lo) >= pz, mass(> hi) < pz."""
    lo = jnp.min(key) - 1
    hi = jnp.max(key)            # mass(> max) == 0 < pz for top_p > 0

    def body(_, carry):
        lo, hi = carry
        mid = _mid(lo, hi)
        mass = jnp.sum(jnp.where(key > mid, e, 0.0))
        take = mass >= pz
        return (jnp.where(take, mid, lo), jnp.where(take, hi, mid))

    lo, _ = jax.lax.fori_loop(0, _BISECT_ITERS, body, (lo, hi))
    return jnp.min(jnp.where(key > lo, key, _INT32_MAX))


def _filter_row(x: jnp.ndarray, top_k: Optional[int],
                top_p: Optional[float]) -> jnp.ndarray:
    """The shared row transform, semantics of serving.sampling.filter_logits
    with the sorts replaced by bisections. x: [1, V] f32, ALREADY
    temperature-scaled by the wrapper — scaling outside the kernel keeps
    kept values bitwise identical to the reference (the in-kernel divide
    can round differently from the surrounding program's), and the kernel
    itself only compares and masks."""
    v = x.shape[-1]
    if top_k is not None and top_k < v:
        key = _order_key(x)
        kth = _bisect_kth_key(key, top_k)
        x = jnp.where(key >= kth, x, _NEG_CAP)
    if top_p is not None and top_p < 1.0:
        key = _order_key(x)
        m = jnp.max(x)
        e = jnp.exp(x - m)       # masked entries underflow to exact zeros
        pz = jnp.float32(top_p) * jnp.sum(e)
        kth = _bisect_top_p_key(key, e, pz)
        x = jnp.where(key >= kth, x, _NEG_CAP)
    return x


def _sampling_kernel(logits_ref, *rest, temperature, top_k, top_p, v,
                     emit):
    """Grid programs over rows (logits pre-scaled by temperature).
    emit='logits' writes the filtered row; emit='tokens' additionally
    draws (argmax, or Gumbel-max when a gumbel row operand is present)
    and writes one int32 per row."""
    if emit == "tokens" and temperature != 0.0:
        gumbel_ref, out_ref = rest
    else:
        (out_ref,) = rest
    x = logits_ref[...].astype(jnp.float32)            # [1, v]
    x = _filter_row(x, top_k, top_p)
    if emit == "logits":
        out_ref[...] = x
        return
    if temperature != 0.0:
        x = x + gumbel_ref[...]
    m = jnp.max(x)
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, v), 1)
    # first-index argmax: identical tie-break to jnp.argmax
    out_ref[0, 0] = jnp.min(jnp.where(x == m, idx, jnp.int32(v)))


def sampling_supported(b: int, v: int) -> bool:
    """Kernel feasibility: lane-aligned vocab that fits the VMEM row
    budget. Callers fall back to the sort-based reference otherwise."""
    return b >= 1 and v % 128 == 0 and v <= _MAX_VOCAB


def threshold_filter_logits(logits: jnp.ndarray, temperature: float,
                            top_k: Optional[int],
                            top_p: Optional[float] = None) -> jnp.ndarray:
    """Fused sort-free filter over [b, V] logits -> filtered f32 [b, V].
    Same masked-logit contract as serving.sampling.filter_logits (masked
    entries pinned at -1e10); caller guarantees sampling_supported()."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    if temperature != 0.0:
        logits = logits / temperature
    kernel = functools.partial(_sampling_kernel, temperature=temperature,
                               top_k=top_k, top_p=top_p, v=v, emit="logits")
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, v), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, v), jnp.float32),
        interpret=interpret_mode(),
    )(logits)


def fused_sample(logits: jnp.ndarray, gumbel: Optional[jnp.ndarray],
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float] = None) -> jnp.ndarray:
    """Fused filter + draw over [b, V] logits -> int32 tokens [b].
    temperature == 0: first-index argmax, bit-identical to the composed
    greedy path. temperature > 0: Gumbel-max with the caller's [b, V]
    gumbel noise. Caller guarantees sampling_supported()."""
    b, v = logits.shape
    sample = temperature != 0.0
    logits = logits.astype(jnp.float32)
    if sample:
        logits = logits / temperature
    kernel = functools.partial(_sampling_kernel, temperature=temperature,
                               top_k=top_k, top_p=top_p, v=v, emit="tokens")
    in_specs = [pl.BlockSpec((1, v), lambda i: (i, 0))]
    operands = [logits]
    if sample:
        if gumbel is None:
            raise ValueError("temperature != 0 needs gumbel noise")
        in_specs.append(pl.BlockSpec((1, v), lambda i: (i, 0)))
        operands.append(gumbel.astype(jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.int32),
        interpret=interpret_mode(),
    )(*operands)
    return out[:, 0]
