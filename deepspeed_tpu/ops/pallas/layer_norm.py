"""Fused LayerNorm, Pallas/TPU.

Reference analogue: ``csrc/transformer/normalize_kernels.cu`` (2121 LoC of
fused layer-norm fwd/bwd variants, incl. residual fusions) exposed through
the transformer kernel. Here: one row-parallel Pallas kernel each for
forward and input-gradient; the (small) parameter gradients are XLA
reductions. Saves mean/rstd for the backward pass like the reference's
training kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode, rows_block


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean[..., 0]
    rstd_ref[...] = rstd[..., 0]


def _dx_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)
    mean = mean_ref[...][..., None]
    rstd = rstd_ref[...][..., None]
    xhat = (x - mean) * rstd
    wdy = dy * gamma
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)




def _ln_fwd(x, gamma, beta, eps):
    orig_shape = x.shape
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    bn = rows_block(n, 256)
    kernel = functools.partial(_fwd_kernel, eps=eps)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2, gamma, beta)
    return y.reshape(orig_shape), (x2, gamma, mean, rstd, orig_shape)


def _ln_bwd(eps, res, g):
    x2, gamma, mean, rstd, orig_shape = res
    d = x2.shape[-1]
    n = x2.shape[0]
    dy2 = g.reshape(-1, d)
    bn = rows_block(n, 256)
    dx = pl.pallas_call(
        _dx_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=interpret_mode(),
    )(x2, gamma, mean, rstd, dy2)
    # parameter grads: plain XLA cross-row reductions
    xhat = (x2.astype(jnp.float32) - mean[:, None]) * rstd[:, None]
    dyf = dy2.astype(jnp.float32)
    dgamma = jnp.sum(dyf * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(dyf, axis=0).astype(gamma.dtype)
    return dx.reshape(orig_shape), dgamma, dbeta


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_pallas(x, gamma, beta, eps: float = 1e-5):
    y, _ = _ln_fwd(x, gamma, beta, eps)
    return y


def _layer_norm_fwd(x, gamma, beta, eps):
    return _ln_fwd(x, gamma, beta, eps)


_layer_norm_pallas.defvjp(_layer_norm_fwd, _ln_bwd)


def layer_norm(x, gamma, beta, eps: float = 1e-5):
    """Fused layer norm over the last dim. x: [..., D]; gamma/beta: [D].
    Row counts TPU can't tile (no block >= 8 divides) fall back to XLA."""
    import numpy as _n
    if rows_block(int(_n.prod(x.shape[:-1])), 256) == 0:
        xf = x.astype(jnp.float32)
        mean = xf.mean(-1, keepdims=True)
        var = ((xf - mean) ** 2).mean(-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps)
        return (y * gamma.astype(jnp.float32)
                + beta.astype(jnp.float32)).astype(x.dtype)
    return _layer_norm_pallas(x, gamma, beta, eps)
