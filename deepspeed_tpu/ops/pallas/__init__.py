"""Pallas TPU kernels (reference analogue: ``csrc/`` CUDA kernels)."""

from .flash_attention import flash_attention
from .gelu import bias_gelu, gelu
from .layer_norm import layer_norm
from .softmax import fused_softmax, masked_softmax
