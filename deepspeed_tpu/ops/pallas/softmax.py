"""Fused (masked) softmax, Pallas/TPU.

Reference analogue: ``csrc/transformer/softmax_kernels.cu`` (training) and
the inference ``softmax`` kernel with triangular/local masking modes
(``csrc/transformer/inference/csrc/softmax.cu``). Supports the same masking
vocabulary: none, causal (triangular), and an additive attention mask.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode, rows_block

NEG_INF = -1e30


def _fwd_kernel(x_ref, y_ref, *, causal, row_offset_per_block, block_rows):
    x = x_ref[...].astype(jnp.float32)                  # [bn, S]
    if causal:
        i = pl.program_id(0)
        s = x.shape[-1]
        # global row index within the [S, S] score matrix
        rows = (i * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, x.shape, x.ndim - 2)) % row_offset_per_block
        cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
        x = jnp.where(rows >= cols, x, NEG_INF)
    m = jnp.max(x, axis=-1, keepdims=True)
    p = jnp.exp(x - m)
    y_ref[...] = (p / jnp.sum(p, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _bwd_kernel(y_ref, dy_ref, dx_ref):
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    dot = jnp.sum(y * dy, axis=-1, keepdims=True)
    dx_ref[...] = (y * (dy - dot)).astype(dx_ref.dtype)




def _softmax_fwd(x, causal):
    orig = x.shape
    s = x.shape[-1]
    rows_per_mat = x.shape[-2] if x.ndim >= 2 else 1
    x2 = x.reshape(-1, s)
    n = x2.shape[0]
    bn = rows_block(n, 128)
    kernel = functools.partial(_fwd_kernel, causal=causal,
                               row_offset_per_block=rows_per_mat,
                               block_rows=bn)
    y = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, s), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, s), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, s), x.dtype),
        interpret=interpret_mode(),
    )(x2)
    return y.reshape(orig), (y, orig)


def _softmax_bwd(causal, res, g):
    y, orig = res
    s = y.shape[-1]
    dy2 = g.reshape(-1, s)
    n = dy2.shape[0]
    bn = rows_block(n, 128)
    dx = pl.pallas_call(
        _bwd_kernel,
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, s), lambda i: (i, 0)),
                  pl.BlockSpec((bn, s), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, s), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, s), dy2.dtype),
        interpret=interpret_mode(),
    )(y, dy2)
    return (dx.reshape(orig),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fused_softmax_pallas(x, causal: bool = False):
    y, _ = _softmax_fwd(x, causal)
    return y


_fused_softmax_pallas.defvjp(lambda x, causal: _softmax_fwd(x, causal),
                             _softmax_bwd)


def fused_softmax(x, causal: bool = False):
    """Softmax over the last dim with optional causal (triangular) masking.
    For causal masking x must be [..., S, S] score matrices. Row counts TPU
    can't tile fall back to XLA."""
    import numpy as _n
    if rows_block(int(_n.prod(x.shape[:-1])), 128) == 0:
        if causal:
            s_len = x.shape[-1]
            tri = jnp.tril(jnp.ones((s_len, s_len), bool))
            x = jnp.where(tri, x, -jnp.inf)
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1).astype(x.dtype)
    return _fused_softmax_pallas(x, causal)


def masked_softmax(x, mask: Optional[jnp.ndarray] = None,
                   causal: bool = False, scale: float = 1.0):
    """Reference ``attn_softmax`` semantics: optional pre-scale + additive
    mask, then fused softmax (inference softmax.cu applies alibi/mask the
    same way)."""
    if scale != 1.0:
        x = x * scale
    if mask is not None:
        x = x + mask
    return fused_softmax(x, causal)
