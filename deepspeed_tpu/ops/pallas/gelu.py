"""Fused bias + GeLU, Pallas/TPU.

Reference analogue: ``csrc/transformer/gelu_kernels.cu`` (330 LoC:
``gelu_kernel``, ``fused_bias_gelu``, ``d_gelu_func``) and the inference
``bias_gelu`` binding. Uses the same tanh approximation as the reference
kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode, rows_block

_SQRT_2_OVER_PI = 0.7978845608028654


def _gelu(x):
    return 0.5 * x * (1.0 + jnp.tanh(_SQRT_2_OVER_PI * (x + 0.044715 * x ** 3)))


def _dgelu(x):
    # d/dx of the tanh-approximated gelu (reference d_gelu_func,
    # gelu_kernels.cu)
    t = jnp.tanh(_SQRT_2_OVER_PI * (x + 0.044715 * x ** 3))
    dt = (1.0 - t * t) * _SQRT_2_OVER_PI * (1.0 + 3 * 0.044715 * x * x)
    return 0.5 * (1.0 + t) + 0.5 * x * dt


def _fwd_kernel(x_ref, b_ref, y_ref):
    x = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = _gelu(x).astype(y_ref.dtype)


def _bwd_kernel(x_ref, b_ref, dy_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    dx_ref[...] = (_dgelu(x) * dy_ref[...].astype(jnp.float32)).astype(dx_ref.dtype)




def _run_rowwise(kernel, inputs, d, out_dtype):
    n = inputs[0].shape[0]
    bn = rows_block(n, 256)
    specs = []
    for a in inputs:
        if a.ndim == 1:
            specs.append(pl.BlockSpec((d,), lambda i: (0,)))
        else:
            specs.append(pl.BlockSpec((bn, d), lambda i: (i, 0)))
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=specs,
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), out_dtype),
        interpret=interpret_mode(),
    )(*inputs)


@jax.custom_vjp
def _bias_gelu_pallas(x, bias):
    orig = x.shape
    d = x.shape[-1]
    y = _run_rowwise(_fwd_kernel, (x.reshape(-1, d), bias), d, x.dtype)
    return y.reshape(orig)


def _bias_gelu_fwd(x, bias):
    return _bias_gelu_pallas(x, bias), (x, bias)


def _bias_gelu_bwd(res, g):
    x, bias = res
    orig = x.shape
    d = x.shape[-1]
    dx = _run_rowwise(_bwd_kernel,
                      (x.reshape(-1, d), bias, g.reshape(-1, d)), d, x.dtype)
    dx = dx.reshape(orig)
    dbias = jnp.sum(dx.astype(jnp.float32),
                    axis=tuple(range(x.ndim - 1))).astype(bias.dtype)
    return dx, dbias


_bias_gelu_pallas.defvjp(_bias_gelu_fwd, _bias_gelu_bwd)


def bias_gelu(x, bias):
    """gelu(x + bias) fused. x: [..., D]; bias: [D]. Row counts TPU can't
    tile fall back to XLA (which fuses this fine anyway)."""
    import numpy as _n
    if rows_block(int(_n.prod(x.shape[:-1])), 256) == 0:
        return jax.nn.gelu(x + bias, approximate=True)
    return _bias_gelu_pallas(x, bias)


def gelu(x):
    """Unfused-bias variant (zero bias)."""
    return bias_gelu(x, jnp.zeros((x.shape[-1],), x.dtype))
