"""Flash attention, Pallas/TPU.

This is the TPU-native replacement for the reference's fused attention
kernels — the training-side softmax/attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, ``ds_transformer_cuda.cpp``) and
the inference ``softmax_context`` kernel family
(``csrc/transformer/inference/csrc/softmax.cu``). Instead of materializing
the [S, S] score matrix in HBM, K/V stream through VMEM one [block_k, D]
tile at a time with an online-softmax accumulator (Flash Attention,
arXiv:2205.14135), so HBM traffic is O(S·D) and VMEM residency is
O(block²) regardless of sequence length — the k loop is the innermost
*grid* dimension with accumulators in VMEM scratch, so long sequences never
blow the ~16 MB VMEM budget.

Layout: q, k, v are [B, S, H, D] (model layout); kernels run per (batch,
head). The backward pass recomputes attention per tile from the saved
per-row logsumexp — the rematerialization trade the reference makes with
activation checkpointing, here at kernel granularity.

On non-TPU backends the kernels run in Pallas interpret mode, which is how
the CPU test mesh exercises them (tests/test_pallas_ops.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode

NEG_INF = -1e30


def _causal_mask(s, qi, ki, block_q, block_k):
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, block_q, block_k, causal):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip fully-masked tiles (strictly above the diagonal)
    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * corr + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + jax.lax.dot(
            p, vb, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[...] + jnp.log(l_safe))[:, None]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    b, s, h, d = q.shape
    # kernel layout [B, H, S, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    nq, nk = s // block_q, s // block_k

    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # stats carry a trailing singleton lane dim: TPU lowering needs
            # the last two block dims divisible by (8, 128) or equal to the
            # array dims — (block_q, 1) qualifies, (1, block_q) does not
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), (qt, kt, vt, out, lse)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block_q, block_k, causal):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot(
            ds, kb, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block_q,
                    block_k, causal):
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (not causal) or (qi * block_q + block_q - 1 >= ki * block_k)

    @pl.when(live)
    def _compute():
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0].astype(jnp.float32)
        dob = do_ref[0, 0].astype(jnp.float32)
        lseb = lse_ref[0, 0, :, 0]
        deltab = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lseb[:, None])                     # [bq, bk]
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab[:, None]) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    qt, kt, vt, out, lse = res
    b, h, s, d = qt.shape
    dot = g.transpose(0, 2, 1, 3)                          # [B,H,S,D]
    delta = jnp.sum(dot.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                # [B,H,S,1]
    nq, nk = s // block_q, s // block_k

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale,
                                  block_q=block_q, block_k=block_k,
                                  causal=causal)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret_mode(),
    )(qt, kt, vt, dot, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   causal=causal)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), kt.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), vt.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret_mode(),
    )(qt, kt, vt, dot, lse, delta)

    tr = lambda x: x.transpose(0, 2, 1, 3)
    return tr(dq), tr(dk), tr(dv)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


_flash_attention.defvjp(_flash_attention_fwd, _flash_bwd)


def _reference_attention(q, k, v, causal, scale):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pick_block(s: int, prefer: int) -> Optional[int]:
    """Largest power-of-two tile <= prefer that divides s (or s itself when
    the whole sequence fits in one tile)."""
    if s <= prefer:
        return s
    for b in (prefer, 512, 256, 128):
        if s % b == 0:
            return b
    return None


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024):
    """Fused attention. q, k, v: [B, S, H, D] -> [B, S, H, D].

    Default 1024-wide tiles measured fastest on v5e at seq 1024 (2x over
    128x128); sequences that don't tile at the preferred size degrade to the
    largest power-of-two tile that divides S, and only fall back to the XLA
    einsum path when no tile >=128 divides S (dynamic/tiny shapes) —
    mirroring the reference's kernel-compatibility gating (op_builder
    ``is_compatible`` checks).
    """
    b, s, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    if bq is None or bk is None:
        return _reference_attention(q, k, v, causal, scale)
    return _flash_attention(q, k, v, causal, scale, bq, bk)
