"""Shared helpers for the Pallas kernel modules."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Run kernels in the Pallas interpreter off-TPU (CPU test mesh)."""
    return jax.default_backend() != "tpu"


def rows_block(n_rows: int, max_block: int = 256) -> int:
    """Largest power-of-two row-block <= max_block dividing n_rows.
    Returns 0 when no block >= 8 divides (TPU Mosaic needs the
    second-to-last block dim to be a multiple of the 8-row sublane tile or
    equal to the array dim) — callers fall back to the XLA implementation,
    like flash_attention does for unsupported shapes."""
    cand = max_block
    while cand >= 8:
        if n_rows % cand == 0:
            return cand
        cand //= 2
    return n_rows if n_rows < 8 else 0
