"""Shared helpers for the Pallas kernel modules."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Run kernels in the Pallas interpreter off-TPU (CPU test mesh)."""
    return jax.default_backend() != "tpu"


def rows_block(n_rows: int, max_block: int = 256) -> int:
    """Largest power-of-two row-block <= max_block dividing n_rows."""
    cand = max_block
    while cand > 1:
        if n_rows % cand == 0:
            return cand
        cand //= 2
    return 1
