"""Fused LAMB (reference: csrc/lamb/fused_lamb_cuda_kernel.cu via
ops/lamb/fused_lamb.py:189). Per-tensor trust ratio = ||w|| / ||update||,
computed with jnp norms — on TPU the reductions fuse into the update kernel.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LambState(NamedTuple):
    count: jnp.ndarray
    mu: any
    nu: any


def fused_lamb(learning_rate=1e-3,
               betas=(0.9, 0.999),
               eps: float = 1e-6,
               weight_decay: float = 0.0,
               max_coeff: float = 10.0,
               min_coeff: float = 0.01,
               bias_correction: bool = True) -> optax.GradientTransformation:
    b1, b2 = betas

    def init(params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return LambState(count=jnp.zeros((), jnp.int32),
                         mu=jax.tree.map(z, params), nu=jax.tree.map(z, params))

    def update(grads, state, params=None):
        assert params is not None, "LAMB needs params for the trust ratio"
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(m.dtype), state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(v.dtype)), state.nu, grads)
        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = jnp.ones((), jnp.float32)

        def upd(m, v, p):
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p.astype(u.dtype)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(u)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                1.0)
            return (-lr * trust * u).astype(p.dtype)

        updates = jax.tree.map(upd, mu, nu, params)
        return updates, LambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)
