"""Module injection (reference: deepspeed/module_inject/)."""

from .policies import (HFGPT2Policy, HFGPTNeoPolicy, load_hf_model,
                       policy_for)
