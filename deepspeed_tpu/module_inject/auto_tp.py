"""Policy-free automatic tensor parallelism for arbitrary flax param trees.

Reference: ``replace_wo_policy`` (``module_inject/replace_module.py:502``)
— for architectures without a hand-written policy, every Linear is split
column-wise (``LinearLayer``) except the ones that write the residual
stream, which become ``LinearAllreduce`` (row-split + allreduce).

TPU redesign: "replacing modules" is unnecessary — assigning a
PartitionSpec to each kernel IS the replacement, and GSPMD inserts the
psum after row-split matmuls automatically (the LinearAllreduce). What
remains of the reference's job is the CLASSIFICATION: which matrices split
which way. Two signals, name first then shape:

  * name patterns (the sharding-rule vocabulary + common HF spellings);
  * shape: an expanding kernel [d, k*d] is column-parallel, a contracting
    kernel [k*d, d] is row-parallel (the Linear that contracts back to the
    hidden size is the residual writer the reference row-splits);
    square kernels with no name signal stay replicated (safe default —
    sharding a square matmul wrongly changes numerics under psum).

Embeddings split on the vocab/feature axis like the reference's embedding
patch (replace_module.py:575); 1-D params (biases, LN) follow their
matrix: column-split kernels get column-split biases, row-split kernels
keep replicated biases (the psum already sums the partial products; a
sharded bias would be added tp times).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.logging import logger

COLUMN_PAT = re.compile(
    r"(qkv|query|key|value|q_proj|k_proj|v_proj|up_proj|gate_proj|fc_in|"
    r"wi|w1|w3|lm_head|intermediate)")
ROW_PAT = re.compile(r"(out_proj|o_proj|down_proj|dense_4h_to_h|fc_out|"
                     r"wo|w2|output)")
EMBED_PAT = re.compile(r"(wte|wpe|wtt|embed|embedding)")


def classify(path: str, shape: Tuple[int, ...]) -> Optional[str]:
    """-> 'column' | 'row' | 'embed' | None (replicate)."""
    if EMBED_PAT.search(path):
        return "embed"
    if len(shape) < 2:
        return None  # 1-D handled relative to its parent kernel
    if COLUMN_PAT.search(path):
        return "column"
    if ROW_PAT.search(path):
        return "row"
    d_in, d_out = shape[-2], shape[-1]
    if d_out >= 2 * d_in:
        return "column"
    if d_in >= 2 * d_out:
        return "row"
    return None


def infer_tp_specs(params, report: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree for a generic params tree (the auto-TP walk).

    Kernels: column -> shard last dim on 'tp'; row -> shard second-to-last.
    Biases: sharded only when their sibling kernel is column-split.
    Works on scan-stacked trees (leading layer axes are untouched)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    # sibling kernel classification for bias decisions
    kinds = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        kinds[key] = classify(key, np.shape(leaf))

    specs = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        shape = np.shape(leaf)
        kind = kinds[key]
        nd = len(shape)
        if kind == "embed" and nd >= 2:
            spec = [None] * nd
            spec[-1] = "tp"              # feature axis; gather is free
        elif kind == "column" and nd >= 2:
            spec = [None] * nd
            spec[-1] = "tp"
        elif kind == "row" and nd >= 2:
            spec = [None] * nd
            spec[-2] = "tp"
        elif nd >= 1 and (getattr(path[-1], "key", None) or
                          getattr(path[-1], "name", "")) == "bias":
            parent = key.rsplit("['bias']", 1)[0] + "['kernel']"
            spec = [None] * nd
            if kinds.get(parent) == "column":
                spec[-1] = "tp"
        else:
            spec = [None] * nd
        specs.append(P(*spec))
        if report:
            logger.info(f"auto-TP: {key} {shape} -> {kind or 'replicate'} "
                        f"{specs[-1]}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def auto_tp_shardings(params, mesh) -> Dict[str, Any]:
    specs = infer_tp_specs(params)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
