"""Injection policies: map HuggingFace checkpoints into our model families.

Reference analogue: ``deepspeed/module_inject/replace_policy.py`` — the
per-architecture weight-extraction adapters (``HFGPT2LayerPolicy``,
``HFGPTNEOLayerPolicy``:113, ``MegatronLayerPolicy``:203 ...) consumed by
``replace_transformer_layer`` (``replace_module.py:124``), which slices
qkv/mlp weights across TP ranks (``ReplaceWithTensorSlicing.qkv_copy``:55).

TPU-native: a policy converts an HF state dict (torch CPU tensors or
numpy) into (GPTConfig, flax param tree); TP "slicing" is not done here —
placement against the mesh's NamedShardings at load time IS the slicing
(runtime/sharding.py tp specs).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..models.gpt import GPTConfig


def _np(t):
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _stack(sd: Dict[str, Any], fmt: str, n: int, transform=None):
    mats = [_np(sd[fmt.format(i)]) for i in range(n)]
    if transform is not None:
        mats = [transform(m) for m in mats]
    return np.stack(mats)


class HFGPT2Policy:
    """GPT-2 family (reference HFGPT2LayerPolicy / client_module gpt2).

    HF GPT2 uses Conv1D ([in, out] kernels — already flax Dense layout)
    with fused c_attn = [q|k|v], matching our qkv Dense split order.
    """

    @staticmethod
    def config_from_hf(hf_config) -> GPTConfig:
        import jax.numpy as jnp
        return GPTConfig(
            vocab_size=hf_config.vocab_size,
            max_seq_len=hf_config.n_positions,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            d_model=hf_config.n_embd,
            d_ff=hf_config.n_inner or 4 * hf_config.n_embd,
            rotary=False, parallel_residual=False, tie_embeddings=True,
            dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True, remat=False)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int) -> Dict[str, Any]:
        sd = {k.removeprefix("transformer."): v
              for k, v in state_dict.items()}
        blocks = {
            "ln_1": {"scale": _stack(sd, "h.{}.ln_1.weight", n_layer),
                     "bias": _stack(sd, "h.{}.ln_1.bias", n_layer)},
            "ln_2": {"scale": _stack(sd, "h.{}.ln_2.weight", n_layer),
                     "bias": _stack(sd, "h.{}.ln_2.bias", n_layer)},
            "attn": {
                "qkv": {"kernel": _stack(sd, "h.{}.attn.c_attn.weight", n_layer),
                        "bias": _stack(sd, "h.{}.attn.c_attn.bias", n_layer)},
                "out_proj": {"kernel": _stack(sd, "h.{}.attn.c_proj.weight", n_layer),
                             "bias": _stack(sd, "h.{}.attn.c_proj.bias", n_layer)},
            },
            "mlp": {
                "up_proj": {"kernel": _stack(sd, "h.{}.mlp.c_fc.weight", n_layer),
                            "bias": _stack(sd, "h.{}.mlp.c_fc.bias", n_layer)},
                "down_proj": {"kernel": _stack(sd, "h.{}.mlp.c_proj.weight", n_layer),
                              "bias": _stack(sd, "h.{}.mlp.c_proj.bias", n_layer)},
            },
        }
        return {
            "wte": {"embedding": _np(sd["wte.weight"])},
            "wpe": _np(sd["wpe.weight"]),
            "blocks": blocks,
            "ln_f": {"scale": _np(sd["ln_f.weight"]),
                     "bias": _np(sd["ln_f.bias"])},
        }


class HFGPTNeoPolicy:
    """GPT-Neo (reference HFGPTNEOLayerPolicy:113): separate q/k/v Linears
    ([out, in] torch layout -> transpose), no attn biases on q/k/v,
    **unscaled** attention scores (qk_scale=1.0) and alternating
    global/local(window-256) layers per ``config.attention_layers`` —
    heterogeneous layers force scan_layers=False."""

    @staticmethod
    def config_from_hf(hf_config) -> GPTConfig:
        import jax.numpy as jnp
        windows = tuple(
            hf_config.window_size if t == "local" else None
            for t in hf_config.attention_layers)
        return GPTConfig(
            vocab_size=hf_config.vocab_size,
            max_seq_len=hf_config.max_position_embeddings,
            num_layers=hf_config.num_layers,
            num_heads=hf_config.num_heads,
            d_model=hf_config.hidden_size,
            d_ff=hf_config.intermediate_size or 4 * hf_config.hidden_size,
            rotary=False, tie_embeddings=True,
            qk_scale=1.0, attn_windows=windows,
            dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=False, remat=False)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int) -> Dict[str, Any]:
        sd = {k.removeprefix("transformer."): v
              for k, v in state_dict.items()}
        d = _np(sd["h.0.attn.attention.q_proj.weight"]).shape[1]

        def qkv_kernel(i):
            q = _np(sd[f"h.{i}.attn.attention.q_proj.weight"]).T
            k = _np(sd[f"h.{i}.attn.attention.k_proj.weight"]).T
            v = _np(sd[f"h.{i}.attn.attention.v_proj.weight"]).T
            return np.concatenate([q, k, v], axis=1)

        def qkv_bias(i):
            z = np.zeros((d,), np.float32)
            def get(name):
                key = f"h.{i}.attn.attention.{name}.bias"
                return _np(sd[key]) if key in sd else z
            return np.concatenate([get("q_proj"), get("k_proj"),
                                   get("v_proj")])

        out = {
            "wte": {"embedding": _np(sd["wte.weight"])},
            "wpe": _np(sd["wpe.weight"]),
            "ln_f": {"scale": _np(sd["ln_f.weight"]),
                     "bias": _np(sd["ln_f.bias"])},
        }
        for i in range(n_layer):  # per-layer blocks (no scan stacking)
            out[f"block_{i}"] = {
                "ln_1": {"scale": _np(sd[f"h.{i}.ln_1.weight"]),
                         "bias": _np(sd[f"h.{i}.ln_1.bias"])},
                "ln_2": {"scale": _np(sd[f"h.{i}.ln_2.weight"]),
                         "bias": _np(sd[f"h.{i}.ln_2.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_kernel(i), "bias": qkv_bias(i)},
                    "out_proj": {
                        "kernel": _np(sd[f"h.{i}.attn.attention.out_proj.weight"]).T,
                        "bias": _np(sd[f"h.{i}.attn.attention.out_proj.bias"])},
                },
                "mlp": {
                    "up_proj": {"kernel": _np(sd[f"h.{i}.mlp.c_fc.weight"]).T,
                                "bias": _np(sd[f"h.{i}.mlp.c_fc.bias"])},
                    "down_proj": {"kernel": _np(sd[f"h.{i}.mlp.c_proj.weight"]).T,
                                  "bias": _np(sd[f"h.{i}.mlp.c_proj.bias"])},
                },
            }
        return out


class HFGPTJPolicy:
    """GPT-J (reference HFGPTJLayerPolicy, replace_policy.py:158): parallel
    residual with ONE shared LayerNorm (mapped onto both ln_1/ln_2 — same
    math), separate bias-free q/k/v Linears fused into qkv, GPT-J-style
    interleaved rotary over ``rotary_dim`` (our rotary_embedding's native
    convention), untied lm_head."""

    @staticmethod
    def config_from_hf(hf_config) -> GPTConfig:
        import jax.numpy as jnp
        head_dim = hf_config.n_embd // hf_config.n_head
        return GPTConfig(
            vocab_size=hf_config.vocab_size,
            max_seq_len=hf_config.n_positions,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            d_model=hf_config.n_embd,
            d_ff=hf_config.n_inner or 4 * hf_config.n_embd,
            rotary=True, rotary_pct=hf_config.rotary_dim / head_dim,
            parallel_residual=True, tie_embeddings=False,
            dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True, remat=False)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int) -> Dict[str, Any]:
        sd = {k.removeprefix("transformer."): v
              for k, v in state_dict.items()}
        d = _np(sd["h.0.attn.q_proj.weight"]).shape[1]

        def qkv_kernel(i):
            return np.concatenate(
                [_np(sd[f"h.{i}.attn.{n}_proj.weight"]).T
                 for n in ("q", "k", "v")], axis=1)

        shared_ln = {"scale": _stack(sd, "h.{}.ln_1.weight", n_layer),
                     "bias": _stack(sd, "h.{}.ln_1.bias", n_layer)}
        blocks = {
            "ln_1": shared_ln,
            "ln_2": {k: v.copy() for k, v in shared_ln.items()},
            "attn": {
                "qkv": {"kernel": np.stack([qkv_kernel(i)
                                            for i in range(n_layer)]),
                        "bias": np.zeros((n_layer, 3 * d), np.float32)},
                "out_proj": {
                    "kernel": _stack(sd, "h.{}.attn.out_proj.weight",
                                     n_layer, transform=lambda m: m.T),
                    "bias": np.zeros((n_layer, d), np.float32)},
            },
            "mlp": {
                "up_proj": {"kernel": _stack(sd, "h.{}.mlp.fc_in.weight",
                                             n_layer,
                                             transform=lambda m: m.T),
                            "bias": _stack(sd, "h.{}.mlp.fc_in.bias",
                                           n_layer)},
                "down_proj": {"kernel": _stack(sd, "h.{}.mlp.fc_out.weight",
                                               n_layer,
                                               transform=lambda m: m.T),
                              "bias": _stack(sd, "h.{}.mlp.fc_out.bias",
                                             n_layer)},
            },
        }
        out = {
            "wte": {"embedding": _np(sd["wte.weight"])},
            "blocks": blocks,
            "ln_f": {"scale": _np(sd["ln_f.weight"]),
                     "bias": _np(sd["ln_f.bias"])},
        }
        if "lm_head.weight" in sd:
            out["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
        else:  # headless GPTJModel: fall back to the embedding (tied)
            out["lm_head"] = {"kernel": _np(sd["wte.weight"]).T}
        return out


class MegatronGPTPolicy:
    """Megatron-LM GPT checkpoints (reference MegatronLayerPolicy,
    replace_policy.py:203 + MegatronSDLoader key vocabulary,
    state_dict_factory.py:195): input/post_attention layernorms map to
    ln_1/ln_2 of the sequential-residual block; the fused
    ``query_key_value`` is PER-HEAD interleaved [np, 3, hn] in checkpoint
    version >= 1.0 and block-ordered [3, np*hn] in version 0 — both are
    regrouped to our [Q | K | V] column order. Per-mp-rank checkpoint sets
    go through checkpoint/state_dict_factory.py first."""

    @staticmethod
    def _regroup_qkv(w: np.ndarray, num_heads: int, version: float):
        """[3h(, h)] megatron row order -> [3h(, h)] with q|k|v blocks."""
        three_h = w.shape[0]
        hn = three_h // 3 // num_heads
        if version == 0:
            return w                        # already [q|k|v] blocks
        parts = w.reshape(num_heads, 3, hn, *w.shape[1:])
        return np.concatenate(
            [parts[:, j].reshape(num_heads * hn, *w.shape[1:])
             for j in range(3)], axis=0)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int, *,
                num_heads: int, version: float = 2.0) -> Dict[str, Any]:
        sd = {k.removeprefix("model.").removeprefix("language_model."): v
              for k, v in state_dict.items()}
        pre = "transformer.layers.{}."
        rq = MegatronGPTPolicy._regroup_qkv

        def lin(fmt):
            return (_stack(sd, fmt + ".weight", n_layer,
                           transform=lambda m: m.T),
                    _stack(sd, fmt + ".bias", n_layer))

        qk = np.stack([rq(_np(sd[pre.format(i) +
                                 "attention.query_key_value.weight"]),
                          num_heads, version).T for i in range(n_layer)])
        qb = np.stack([rq(_np(sd[pre.format(i) +
                                 "attention.query_key_value.bias"]),
                          num_heads, version) for i in range(n_layer)])
        ok, ob = lin(pre + "attention.dense")
        uk, ub = lin(pre + "mlp.dense_h_to_4h")
        dk, db = lin(pre + "mlp.dense_4h_to_h")
        blocks = {
            "ln_1": {"scale": _stack(sd, pre + "input_layernorm.weight",
                                     n_layer),
                     "bias": _stack(sd, pre + "input_layernorm.bias",
                                    n_layer)},
            "ln_2": {"scale": _stack(
                sd, pre + "post_attention_layernorm.weight", n_layer),
                "bias": _stack(
                    sd, pre + "post_attention_layernorm.bias", n_layer)},
            "attn": {"qkv": {"kernel": qk, "bias": qb},
                     "out_proj": {"kernel": ok, "bias": ob}},
            "mlp": {"up_proj": {"kernel": uk, "bias": ub},
                    "down_proj": {"kernel": dk, "bias": db}},
        }
        return {
            "wte": {"embedding": _np(sd["word_embeddings.weight"])},
            "wpe": _np(sd["position_embeddings.weight"]),
            "blocks": blocks,
            "ln_f": {"scale": _np(sd["transformer.final_layernorm.weight"]),
                     "bias": _np(sd["transformer.final_layernorm.bias"])},
        }


class HFBertPolicy:
    """BERT (reference HFBertLayerPolicy, replace_policy.py:50): torch
    Linear [out, in] -> transpose; q/k/v concatenated into the fused qkv;
    encoder layers stacked on a leading layer axis for the scan."""

    @staticmethod
    def config_from_hf(hf_config):
        import jax.numpy as jnp
        from ..models.bert import BertConfig
        return BertConfig(
            vocab_size=hf_config.vocab_size,
            max_seq_len=hf_config.max_position_embeddings,
            type_vocab_size=hf_config.type_vocab_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            d_model=hf_config.hidden_size,
            d_ff=hf_config.intermediate_size,
            layer_norm_eps=hf_config.layer_norm_eps,
            hidden_dropout=0.0,
            dtype=jnp.float32, param_dtype=jnp.float32, scan_layers=True)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int) -> Dict[str, Any]:
        sd = {k.removeprefix("bert."): v for k, v in state_dict.items()}
        pre = "encoder.layer.{}."

        def lin(fmt):
            return (_stack(sd, fmt + ".weight", n_layer,
                           transform=lambda m: m.T),
                    _stack(sd, fmt + ".bias", n_layer))

        def ln(fmt):
            return {"scale": _stack(sd, fmt + ".weight", n_layer),
                    "bias": _stack(sd, fmt + ".bias", n_layer)}

        qk = [np.concatenate(
            [_np(sd[pre.format(i) + f"attention.self.{n}.weight"]).T
             for n in ("query", "key", "value")], axis=1)
            for i in range(n_layer)]
        qb = [np.concatenate(
            [_np(sd[pre.format(i) + f"attention.self.{n}.bias"])
             for n in ("query", "key", "value")])
            for i in range(n_layer)]
        ok, ob = lin(pre + "attention.output.dense")
        uk, ub = lin(pre + "intermediate.dense")
        dk, db = lin(pre + "output.dense")
        out = {
            "wte": {"embedding": _np(sd["embeddings.word_embeddings.weight"])},
            "wpe": _np(sd["embeddings.position_embeddings.weight"]),
            "wtt": {"embedding":
                    _np(sd["embeddings.token_type_embeddings.weight"])},
            "ln_emb": {"scale": _np(sd["embeddings.LayerNorm.weight"]),
                       "bias": _np(sd["embeddings.LayerNorm.bias"])},
            "blocks": {
                "attn": {
                    "qkv": {"kernel": np.stack(qk), "bias": np.stack(qb)},
                    "out_proj": {"kernel": ok, "bias": ob},
                },
                "ln_attn": ln(pre + "attention.output.LayerNorm"),
                "up_proj": {"kernel": uk, "bias": ub},
                "down_proj": {"kernel": dk, "bias": db},
                "ln_ffn": ln(pre + "output.LayerNorm"),
            },
        }
        if "pooler.dense.weight" in sd:
            out["pooler"] = {"kernel": _np(sd["pooler.dense.weight"]).T,
                             "bias": _np(sd["pooler.dense.bias"])}
        return out


def _export_gpt2(params: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of HFGPT2Policy.convert (the ``revert_transformer_layer``
    analogue, replace_module.py:635): flax tree -> HF GPT-2 state dict
    (Conv1D layout, so kernels pass through untransposed)."""
    p = lambda x: np.asarray(x)
    out = {"transformer.wte.weight": p(params["wte"]["embedding"]),
           "transformer.wpe.weight": p(params["wpe"]),
           "transformer.ln_f.weight": p(params["ln_f"]["scale"]),
           "transformer.ln_f.bias": p(params["ln_f"]["bias"])}
    b = params["blocks"]
    n_layer = p(b["ln_1"]["scale"]).shape[0]
    for i in range(n_layer):
        pre = f"transformer.h.{i}."
        out[pre + "ln_1.weight"] = p(b["ln_1"]["scale"])[i]
        out[pre + "ln_1.bias"] = p(b["ln_1"]["bias"])[i]
        out[pre + "ln_2.weight"] = p(b["ln_2"]["scale"])[i]
        out[pre + "ln_2.bias"] = p(b["ln_2"]["bias"])[i]
        out[pre + "attn.c_attn.weight"] = p(b["attn"]["qkv"]["kernel"])[i]
        out[pre + "attn.c_attn.bias"] = p(b["attn"]["qkv"]["bias"])[i]
        out[pre + "attn.c_proj.weight"] = p(b["attn"]["out_proj"]["kernel"])[i]
        out[pre + "attn.c_proj.bias"] = p(b["attn"]["out_proj"]["bias"])[i]
        out[pre + "mlp.c_fc.weight"] = p(b["mlp"]["up_proj"]["kernel"])[i]
        out[pre + "mlp.c_fc.bias"] = p(b["mlp"]["up_proj"]["bias"])[i]
        out[pre + "mlp.c_proj.weight"] = p(b["mlp"]["down_proj"]["kernel"])[i]
        out[pre + "mlp.c_proj.bias"] = p(b["mlp"]["down_proj"]["bias"])[i]
    out["lm_head.weight"] = out["transformer.wte.weight"]  # tied
    return out


def _export_bert(params: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of HFBertPolicy.convert: flax tree -> HF BERT state dict
    (torch Linear [out, in] layout, fused qkv split back to q/k/v)."""
    # standalone-BertModel key convention (no "bert." prefix — convert
    # strips it either way)
    p = lambda x: np.asarray(x)
    out = {
        "embeddings.word_embeddings.weight": p(params["wte"]["embedding"]),
        "embeddings.position_embeddings.weight": p(params["wpe"]),
        "embeddings.token_type_embeddings.weight":
            p(params["wtt"]["embedding"]),
        "embeddings.LayerNorm.weight": p(params["ln_emb"]["scale"]),
        "embeddings.LayerNorm.bias": p(params["ln_emb"]["bias"]),
    }
    if "pooler" in params:
        out["pooler.dense.weight"] = p(params["pooler"]["kernel"]).T
        out["pooler.dense.bias"] = p(params["pooler"]["bias"])
    b = params["blocks"]
    n_layer = p(b["ln_attn"]["scale"]).shape[0]
    d = p(b["attn"]["qkv"]["kernel"]).shape[1]
    for i in range(n_layer):
        pre = f"encoder.layer.{i}."
        qkv_k = p(b["attn"]["qkv"]["kernel"])[i]        # [d, 3d]
        qkv_b = p(b["attn"]["qkv"]["bias"])[i]
        for j, name in enumerate(("query", "key", "value")):
            out[pre + f"attention.self.{name}.weight"] = \
                qkv_k[:, j * d:(j + 1) * d].T
            out[pre + f"attention.self.{name}.bias"] = \
                qkv_b[j * d:(j + 1) * d]
        out[pre + "attention.output.dense.weight"] = \
            p(b["attn"]["out_proj"]["kernel"])[i].T
        out[pre + "attention.output.dense.bias"] = \
            p(b["attn"]["out_proj"]["bias"])[i]
        out[pre + "attention.output.LayerNorm.weight"] = \
            p(b["ln_attn"]["scale"])[i]
        out[pre + "attention.output.LayerNorm.bias"] = \
            p(b["ln_attn"]["bias"])[i]
        out[pre + "intermediate.dense.weight"] = p(b["up_proj"]["kernel"])[i].T
        out[pre + "intermediate.dense.bias"] = p(b["up_proj"]["bias"])[i]
        out[pre + "output.dense.weight"] = p(b["down_proj"]["kernel"])[i].T
        out[pre + "output.dense.bias"] = p(b["down_proj"]["bias"])[i]
        out[pre + "output.LayerNorm.weight"] = p(b["ln_ffn"]["scale"])[i]
        out[pre + "output.LayerNorm.bias"] = p(b["ln_ffn"]["bias"])[i]
    return out


HFGPT2Policy.export = staticmethod(_export_gpt2)
HFBertPolicy.export = staticmethod(_export_bert)


class HFDistilBertPolicy:
    """DistilBERT (reference HFDistilBertLayerPolicy — the one arch the
    round-3 policy table lacked): BERT-shaped post-LN encoder with no
    token-type embeddings and no pooler; q/k/v live as separate q_lin/
    k_lin/v_lin Linears under transformer.layer.N.attention."""

    @staticmethod
    def config_from_hf(hf_config):
        import jax.numpy as jnp
        from ..models.bert import BertConfig
        return BertConfig(
            vocab_size=hf_config.vocab_size,
            max_seq_len=hf_config.max_position_embeddings,
            type_vocab_size=0,
            use_pooler=False,
            num_layers=hf_config.n_layers,
            num_heads=hf_config.n_heads,
            d_model=hf_config.dim,
            d_ff=hf_config.hidden_dim,
            layer_norm_eps=getattr(hf_config, "layer_norm_eps", 1e-12),
            hidden_dropout=0.0,
            dtype=jnp.float32, param_dtype=jnp.float32, scan_layers=True)

    @staticmethod
    def convert(state_dict: Dict[str, Any], n_layer: int) -> Dict[str, Any]:
        sd = {k.removeprefix("distilbert."): v for k, v in state_dict.items()}
        pre = "transformer.layer.{}."

        def lin(fmt):
            return (_stack(sd, fmt + ".weight", n_layer,
                           transform=lambda m: m.T),
                    _stack(sd, fmt + ".bias", n_layer))

        def ln(fmt):
            return {"scale": _stack(sd, fmt + ".weight", n_layer),
                    "bias": _stack(sd, fmt + ".bias", n_layer)}

        qk = [np.concatenate(
            [_np(sd[pre.format(i) + f"attention.{n}.weight"]).T
             for n in ("q_lin", "k_lin", "v_lin")], axis=1)
            for i in range(n_layer)]
        qb = [np.concatenate(
            [_np(sd[pre.format(i) + f"attention.{n}.bias"])
             for n in ("q_lin", "k_lin", "v_lin")])
            for i in range(n_layer)]
        ok, ob = lin(pre + "attention.out_lin")
        uk, ub = lin(pre + "ffn.lin1")
        dk, db = lin(pre + "ffn.lin2")
        return {
            "wte": {"embedding": _np(sd["embeddings.word_embeddings.weight"])},
            "wpe": _np(sd["embeddings.position_embeddings.weight"]),
            "ln_emb": {"scale": _np(sd["embeddings.LayerNorm.weight"]),
                       "bias": _np(sd["embeddings.LayerNorm.bias"])},
            "blocks": {
                "attn": {
                    "qkv": {"kernel": np.stack(qk), "bias": np.stack(qb)},
                    "out_proj": {"kernel": ok, "bias": ob},
                },
                "ln_attn": ln(pre + "sa_layer_norm"),
                "up_proj": {"kernel": uk, "bias": ub},
                "down_proj": {"kernel": dk, "bias": db},
                "ln_ffn": ln(pre + "output_layer_norm"),
            },
        }


def export_hf_state_dict(model_type: str, params: Dict[str, Any]
                         ) -> Dict[str, Any]:
    """Inverse injection: our param tree back to an HF state dict (numpy),
    usable to hand a trained/tuned model back to the torch ecosystem."""
    pol = policy_for(model_type)
    if not hasattr(pol, "export"):
        raise ValueError(f"no export path for {model_type!r}")
    return pol.export(params)


_POLICIES = {
    "gpt2": HFGPT2Policy,
    "gpt_neo": HFGPTNeoPolicy,
    "gptj": HFGPTJPolicy,
    "bert": HFBertPolicy,
    "distilbert": HFDistilBertPolicy,
    "megatron": MegatronGPTPolicy,
}


def policy_for(model_type: str):
    if model_type not in _POLICIES:
        raise ValueError(
            f"no injection policy for {model_type!r}; have "
            f"{sorted(_POLICIES)}")
    return _POLICIES[model_type]


def load_hf_model(hf_model) -> Tuple[GPTConfig, Dict[str, Any]]:
    """replace_transformer_layer analogue: HF model -> (GPTConfig, params).
    Works on any loaded ``transformers`` model of a supported type."""
    model_type = hf_model.config.model_type
    pol = policy_for(model_type)
    cfg = pol.config_from_hf(hf_model.config)
    params = pol.convert(dict(hf_model.state_dict()), cfg.num_layers)
    return cfg, params
