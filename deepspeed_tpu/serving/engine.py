"""ServingEngine: continuous-batching façade over the inference stack.

Reference analogue: ``deepspeed/inference/engine.py`` serves ONE
``generate`` call at a time; production serving (the ROADMAP north star)
needs many concurrent streams. This engine composes

  * the existing :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
    (TP placement, int8 dequant-in-program, multi-host input handling),
  * a slotted KV arena (serving/kv_cache.py) with per-slot fills,
  * an iteration-level scheduler (serving/scheduler.py),
  * live metrics through the monitor fan-out (serving/metrics.py),

into a serve loop with exactly TWO compiled model programs regardless of
traffic — the CUDA-graph discipline applied to serving:

  prefill  (params, ids[1, P],  len, rng) -> (token[1],  cache)   fixed P
  decode   (params, arena, tok[B], pos[B], rng) -> (token[B], arena)

(plus one trivial non-model copy program that moves a prefilled cache into
its arena slot). Prompts pad to the ``max_prompt_len`` bucket; the decode
batch is always ``max_batch`` wide with retired slots riding as masked-out
lanes, so XLA never sees a new shape after warmup.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..utils.logging import log_dist
from .kv_cache import SlotKVCacheManager
from .metrics import ServingMetrics
from .scheduler import ContinuousBatchScheduler, Request


def sample_tokens(logits, rng, temperature: float, top_k: Optional[int]):
    """Greedy / temperature / top-k sampling over [b, V] logits — the same
    policy as InferenceEngine.generate's sampler."""
    import jax
    import jax.numpy as jnp
    logits = logits.astype(jnp.float32)
    if temperature not in (0.0, 1.0):
        logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e10, logits)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class ServingEngine:
    """Continuous-batching server over a decoder LM.

    Pass an existing ``InferenceEngine`` (keeps its TP/quantization setup),
    or ``model`` + ``model_parameters`` to build one. Minimal use::

        serving = ServingEngine(model, model_parameters=params,
                                max_batch=8, dtype=jnp.float32)
        results = serving.run([prompt_ids_1, prompt_ids_2, ...],
                              max_new_tokens=32)
        results[0].output_ids      # prompt + generated tokens
    """

    def __init__(self, model=None, model_parameters=None, *,
                 engine=None,
                 max_batch: int = 8,
                 max_prompt_len: Optional[int] = None,
                 max_queue: int = 64,
                 temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 monitor=None,
                 emit_every_steps: int = 16,
                 seed: int = 0,
                 **inference_kwargs):
        import jax
        import jax.numpy as jnp

        if engine is None:
            from ..inference.engine import InferenceEngine
            engine = InferenceEngine(model, model_parameters=model_parameters,
                                     **inference_kwargs)
        self.engine = engine
        self.module = engine.module
        cfg = getattr(self.module, "cfg", None)
        max_seq = getattr(cfg, "max_seq_len", None)
        if max_seq is None:
            raise ValueError("ServingEngine needs a model with "
                             "cfg.max_seq_len (the KV arena extent)")
        self.max_batch = int(max_batch)
        self.max_prompt_len = int(max_prompt_len or max_seq)
        if self.max_prompt_len > max_seq:
            raise ValueError(f"max_prompt_len {self.max_prompt_len} exceeds "
                             f"the model's max_seq_len {max_seq}")
        self.temperature = float(temperature)
        self.top_k = top_k

        self.kv = SlotKVCacheManager(self.module, engine.params,
                                     self.max_batch)
        self.scheduler = ContinuousBatchScheduler(
            self.kv.allocator, max_queue=max_queue,
            max_prompt_len=self.max_prompt_len)
        self.metrics = ServingMetrics(monitor,
                                      emit_every_steps=emit_every_steps)
        self._rng = jax.random.PRNGKey(seed)
        self._last_token = np.zeros(self.max_batch, np.int32)

        mat = engine._materialize
        module = self.module
        temperature_, top_k_ = self.temperature, self.top_k

        def prefill(params, ids, true_len, rng):
            pm = mat(params)
            positions = jnp.arange(ids.shape[1])[None, :]
            logits, vc = module.apply({"params": pm}, ids,
                                      positions=positions, mutable=["cache"])
            if isinstance(logits, tuple):
                logits = logits[0]
            last = jax.lax.dynamic_slice_in_dim(
                logits, true_len - 1, 1, axis=1)[:, 0]          # [1, V]
            tok = sample_tokens(last, rng, temperature_, top_k_)
            return tok, vc["cache"]

        def decode(params, cache, tokens, positions, rng):
            pm = mat(params)
            logits, vc = module.apply(
                {"params": pm, "cache": cache}, tokens[:, None],
                positions=positions[:, None], mutable=["cache"])
            if isinstance(logits, tuple):
                logits = logits[0]
            tok = sample_tokens(logits[:, -1], rng, temperature_, top_k_)
            return tok, vc["cache"]

        self._jit_prefill = jax.jit(prefill)
        # donate the arena: XLA updates every slot's KV rows in place
        self._jit_decode = jax.jit(decode, donate_argnums=(1,))
        log_dist(f"serving engine ready: slots={self.max_batch} "
                 f"prefill_bucket={self.max_prompt_len} "
                 f"max_seq={max_seq}", ranks=[0])

    # --------------------------------------------------------------- API
    def submit(self, prompt: Union[Request, Sequence[int], np.ndarray],
               **request_kwargs) -> Request:
        """Enqueue one request (token-id prompt or a prebuilt Request).
        Rejections (bounded queue, oversized prompt) come back as
        ``status == "rejected"`` with ``reject_reason`` set — the
        backpressure signal, not an exception."""
        req = prompt if isinstance(prompt, Request) else Request(
            prompt=np.asarray(prompt, np.int32), **request_kwargs)
        self.metrics.start()
        if not self.scheduler.submit(req):
            self.metrics.on_rejected()
        return req

    def step(self) -> List[Request]:
        """One continuous-batching iteration: admit newly-runnable requests
        into free slots (prefill + arena insert), then one fused decode
        step over all live slots. Returns requests finished this step."""
        before = len(self.scheduler.finished)
        self._admit()
        self._decode_once()
        return self.scheduler.finished[before:]

    def run(self, prompts: Optional[Sequence] = None,
            **request_kwargs) -> List[Request]:
        """Serve until drained. ``prompts``: token-id sequences (or Request
        objects) submitted up front; per-request kwargs (max_new_tokens,
        eos_token_id, deadline_s) apply to all of them. Returns the
        submitted requests in submission order (rejected ones included,
        flagged by status)."""
        submitted = [self.submit(p, **request_kwargs)
                     for p in (prompts or [])]
        while self.scheduler.has_work():
            self.step()
        self.metrics.maybe_emit(self.scheduler.queue_depth,
                                self.kv.occupancy, force=True)
        return submitted

    # ---------------------------------------------------------- internals
    def _next_rng(self):
        import jax
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self) -> None:
        import jax.numpy as jnp
        for req in self.scheduler.admit():
            ids = np.zeros((1, self.max_prompt_len), np.int32)
            ids[0, :req.prompt_len] = req.prompt
            tok, one_cache = self._jit_prefill(
                self.engine.params, jnp.asarray(ids),
                jnp.int32(req.prompt_len), self._next_rng())
            self.kv.insert(one_cache, req.slot, req.prompt_len)
            first = int(np.asarray(tok)[0])
            self._last_token[req.slot] = first
            # may retire the request immediately (max_new_tokens == 1 or
            # an instant EOS) — its slot frees before the decode step
            self.scheduler.record_first_token(req, first)
            self.metrics.on_tokens(1)

    def _decode_once(self) -> None:
        import jax.numpy as jnp
        running = self.scheduler.running
        if not running:
            return
        slots = sorted(running)
        tokens = np.zeros(self.max_batch, np.int32)
        positions = np.zeros(self.max_batch, np.int32)
        for s in slots:
            tokens[s] = self._last_token[s]
            positions[s] = self.kv.fill[s]
        tok, new_cache = self._jit_decode(
            self.engine.params, self.kv.cache, jnp.asarray(tokens),
            jnp.asarray(positions), self._next_rng())
        self.kv.update(new_cache)
        self.kv.allocator.advance(slots)
        tok_host = np.asarray(tok)
        for s in slots:
            self._last_token[s] = int(tok_host[s])
        finished = self.scheduler.step_tokens(
            {s: int(tok_host[s]) for s in slots})
        self.metrics.on_tokens(len(slots))
        self.metrics.on_decode_step()
        self.metrics.on_finished(finished)
        self.metrics.maybe_emit(self.scheduler.queue_depth,
                                self.kv.occupancy)
