"""ServingEngine: continuous-batching façade over the inference stack.

Reference analogue: ``deepspeed/inference/engine.py`` serves ONE
``generate`` call at a time; production serving (the ROADMAP north star)
needs many concurrent streams. This engine composes

  * the existing :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
    (TP placement, int8 dequant-in-program, multi-host input handling),
  * a slotted KV arena (serving/kv_cache.py) with per-slot fills,
  * an iteration-level scheduler (serving/scheduler.py),
  * live metrics through the monitor fan-out (serving/metrics.py),

into a DEVICE-PACED serve loop. The compiled model programs:

  prefill  (params, ids[n, P], lens[n], rng) -> (tok[n], cache)
           bucketed: P is the smallest power-of-two bucket (16/32/64/...)
           covering the batch's longest prompt, n <= max_batch; compiled
           lazily per (n, P) pair so a burst of short prompts stops
           paying ``max_prompt_len`` of padded compute
  decode   (params, arena, tok[B], pos[B], rng) -> (tok[B], arena)
           the PR-1 per-token loop, kept behind ``decode_chunk=1`` as the
           bit-parity reference
  decode_chunk
           (params, arena, tok[B], pos[B], act[B], eos[B], rem[B], rng)
           -> (toks[B, K], valid[B, K], arena, carry...)
           a ``lax.scan`` running K = ``decode_chunk`` decode steps per
           host iteration: sampling, per-slot EOS / token-budget stop
           masking, and KV writes all stay on device; retired lanes pin
           their write index at ``max_seq_len`` (models/gpt.py drops the
           write) so a dead lane never dirties KV rows. The host syncs
           ONCE per chunk and hands the token buffer to the scheduler in
           one ``step_tokens_chunk`` call.

(plus the trivial non-model insert programs that move prefilled caches
into arena slot rows). ``run()`` additionally double-buffers: the next
chunk is enqueued from the previous chunk's device-resident carry BEFORE
the host blocks on its token buffer, so scheduler bookkeeping overlaps
device compute (JAX async dispatch). This converts the serving tier from
host-paced (one dispatch + one sync per token) to device-paced (one per
K tokens) — the difference that shows up wherever dispatch latency
rivals the model's step time.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple, Union

import numpy as np

from ..telemetry import core as telemetry
from ..utils.logging import log_dist
from .kv_cache import SlotKVCacheManager
from .metrics import ServingMetrics
# The sampling policy moved to serving/sampling.py (one reference shared
# by the engine, the speculative verifier, and the fused Pallas epilogue);
# re-exported here for API stability.
from .sampling import (filter_logits, fused_filter_logits,  # noqa: F401
                       fused_sample_tokens, sample_tokens)
from .scheduler import ContinuousBatchScheduler, Request


def default_prefill_buckets(max_prompt_len: int) -> List[int]:
    """Power-of-two prefill buckets from 16 up to ``max_prompt_len``
    (which always caps the list so every admissible prompt has a
    bucket)."""
    out: List[int] = []
    b = 16
    while b < max_prompt_len:
        out.append(b)
        b *= 2
    out.append(max_prompt_len)
    return out


MIGRATE_SCHEMA = "dstpu-migrate-v1"


class MigrationError(RuntimeError):
    """A live KV-block migration could not run — the request is NOT
    movable right now (mid-prefill, unsupported layout) or the target
    cannot host it (block-pool OOM, shape mismatch). The request keeps
    running wherever it already lives; migration failure is a
    load-balancing miss, never a lost stream."""


@dataclasses.dataclass
class _InflightChunk:
    """One enqueued decode chunk: device handles (nothing synced yet) plus
    the slot->request-uid snapshot at launch time, so tokens are never
    attributed to a slot's NEXT occupant."""
    slot_uids: Dict[int, int]
    tokens: Any          # [B, K] device ([B, K*(k+1)] speculative)
    valid: Any           # [B, K] device (lane was live entering the step)
    state: Tuple         # (tok[B], pos[B], act[B], rem[B], eos[B]) device,
    #                      + hist[B, S] in speculative mode
    # dispatch-complete stamp (profiler clock); 0.0 when no profiler is
    # attached — the chunk timeline lane anchors device spans on it
    launch_t: float = 0.0
    # unconditional perf_counter stamp at launch: the collective-overlap
    # gauge accumulates launch->retire wall seconds from it
    wall_t0: float = 0.0


def _load_tuned_config(tuned_config) -> Dict[str, Any]:
    """Normalize a ``tuned_config=`` argument into a flat knob dict.

    Accepts the serving capacity tuner's Pareto JSON document (a path
    or the loaded dict — the best point's config is used), a bare
    ``{"config": {...}}`` point, or a flat knob dict. ``block_size``
    (the tuner's axis name) aliases ``kv_block_size``."""
    import json
    doc = tuned_config
    if isinstance(doc, (str, os.PathLike)):
        with open(doc) as f:
            doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"tuned_config must be a dict or a JSON path, "
                         f"got {type(tuned_config).__name__}")
    schema = doc.get("schema")
    if schema is not None and schema != "dstpu-tuned-v1":
        raise ValueError(f"unsupported tuned_config schema {schema!r} "
                         f"(want dstpu-tuned-v1)")
    if "best" in doc:
        doc = doc["best"]
    elif "pareto" in doc:
        pts = doc["pareto"]
        if not pts:
            raise ValueError("tuned_config has an empty Pareto frontier")
        doc = max(pts, key=lambda p: p.get("tokens_per_s", 0.0))
    cfg = dict(doc.get("config", doc))
    if "block_size" in cfg and "kv_block_size" not in cfg:
        cfg["kv_block_size"] = cfg.pop("block_size")
    return cfg


class ServingEngine:
    """Continuous-batching server over a decoder LM.

    Pass an existing ``InferenceEngine`` (keeps its TP/quantization setup),
    or ``model`` + ``model_parameters`` to build one. Minimal use::

        serving = ServingEngine(model, model_parameters=params,
                                max_batch=8, dtype=jnp.float32)
        results = serving.run([prompt_ids_1, prompt_ids_2, ...],
                              max_new_tokens=32)
        results[0].output_ids      # prompt + generated tokens

    ``decode_chunk`` is the number of decode steps fused into one device
    program invocation (K). ``decode_chunk=1`` is the PR-1 per-token loop
    (one host sync per token); greedy outputs are bit-identical across
    all K. Deadlines are only observed at chunk boundaries — a request
    may overrun its deadline by up to K-1 tokens of device work.
    """

    def __init__(self, model=None, model_parameters=None, *,
                 engine=None,
                 max_batch: int = 8,
                 max_prompt_len: Optional[int] = None,
                 max_queue: int = 64,
                 decode_chunk: int = 8,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 speculative: bool = False,
                 spec_k: int = 4,
                 spec_ngram: int = 2,
                 drafter=None,
                 kv_dtype: str = "auto",
                 monitor=None,
                 emit_every_steps: int = 16,
                 seed: int = 0,
                 paged: bool = False,
                 kv_block_size: int = 16,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefix_cache_capacity: int = 64,
                 tp: int = 1,
                 disaggregate_prefill: bool = False,
                 fused_prefill: bool = False,
                 megakernel: bool = False,
                 prefill_chunk: int = 16,
                 chunk_token_budget: Optional[int] = None,
                 sp_prefill_threshold: Optional[int] = None,
                 tiered_kv: bool = False,
                 tier_dram_bytes: int = 256 << 20,
                 tier_nvme_bytes: Optional[int] = None,
                 tier_spill_dir: Optional[str] = None,
                 tuned_config=None,
                 **inference_kwargs):
        import jax
        import jax.numpy as jnp

        # ---- autotuned defaults (autotuning/serving_tuner.py) ----
        # A Pareto-frontier JSON (path or dict) supplies tuned values
        # for the capacity knobs; an explicitly passed non-default
        # argument always wins over the tuned value.
        self.tuned_config = None
        if tuned_config is not None:
            tuned = _load_tuned_config(tuned_config)
            self.tuned_config = tuned
            _sig = {"decode_chunk": 8, "spec_k": 4, "kv_block_size": 16,
                    "prefill_chunk": 16, "tier_dram_bytes": 256 << 20}
            ns = locals()
            # a null tuned value means "axis off" (e.g. the untiered
            # Pareto corner's tier_dram_bytes) — keep the default
            picked = {k: tuned[k] for k in _sig
                      if tuned.get(k) is not None and ns[k] == _sig[k]}
            decode_chunk = picked.get("decode_chunk", decode_chunk)
            spec_k = picked.get("spec_k", spec_k)
            kv_block_size = picked.get("kv_block_size", kv_block_size)
            prefill_chunk = picked.get("prefill_chunk", prefill_chunk)
            tier_dram_bytes = picked.get("tier_dram_bytes",
                                         tier_dram_bytes)

        if engine is None:
            from ..inference.engine import InferenceEngine
            if int(tp) > 1:
                # the serving-level tp knob rides the inference engine's
                # existing mesh/ShardingRules machinery (mp_size)
                inference_kwargs.setdefault("mp_size", int(tp))
            engine = InferenceEngine(model, model_parameters=model_parameters,
                                     **inference_kwargs)
        self.engine = engine
        mesh = getattr(engine, "mesh", None)
        self.tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        if int(tp) > 1 and self.tp != int(tp):
            raise ValueError(
                f"tp={tp} requested but the engine's mesh has tp={self.tp} "
                f"(pass mp_size={tp} when building the InferenceEngine, or "
                f"drop the engine= argument)")
        self.module = engine.module
        cfg = getattr(self.module, "cfg", None)
        max_seq = getattr(cfg, "max_seq_len", None)
        if max_seq is None:
            raise ValueError("ServingEngine needs a model with "
                             "cfg.max_seq_len (the KV arena extent)")
        self.kv_dtype = str(kv_dtype)
        if self.kv_dtype not in ("auto", "int8"):
            raise ValueError(f"kv_dtype must be 'auto' or 'int8', "
                             f"got {kv_dtype!r}")
        if self.kv_dtype == "int8":
            # rebuild the module with the int8 cache config BEFORE the
            # arena is shaped from it: every cache leaf the engine
            # compiles against (int8 payload + f32 scale leaves) comes
            # from this module's eval_shape
            cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
            self.module = type(self.module)(cfg)
        # ---- fused decode megakernel ----
        # One knob flips the decode stack onto the fused fast path: the
        # Pallas decode kernel (int8 dequant inside the DMA window,
        # in-kernel k+1 speculative verify — decode_impl "auto" resolves
        # to it on TPU and to the partition-friendly einsum elsewhere,
        # so CPU parity gates run the program they always did), the
        # sort-free sampling epilogue (ops/pallas/sampling.py, swapped in
        # below), and — when the mesh has a tp axis under a parallel-
        # residual model — the RS/AG collective/MLP overlap
        # (ops/tp_overlap.py). Greedy outputs are bit-identical with the
        # knob on or off (the megakernel contract, gated by tests);
        # temperature > 0 draws are distributionally identical but
        # consume the rng as Gumbel noise instead of ``categorical``'s
        # internal stream.
        self.megakernel = bool(megakernel)
        if self.megakernel:
            rebuild = {}
            if getattr(cfg, "decode_impl", None) == "xla":
                rebuild["decode_impl"] = "auto"
            if (self.tp > 1 and getattr(cfg, "parallel_residual", False)
                    and hasattr(cfg, "tp_overlap")):
                rebuild["tp_overlap"] = True
            if rebuild:
                cfg = dataclasses.replace(cfg, **rebuild)
                self.module = type(self.module)(cfg)
        self._overlap_active = bool(getattr(cfg, "tp_overlap", False))
        self._overlap_seconds = 0.0
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq)
        self.max_prompt_len = int(max_prompt_len or max_seq)
        if self.max_prompt_len > max_seq:
            raise ValueError(f"max_prompt_len {self.max_prompt_len} exceeds "
                             f"the model's max_seq_len {max_seq}")
        self.decode_chunk = int(decode_chunk)
        if self.decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        # ---- fused chunked prefill (Sarathi-style, in-scan) ----
        # Prompts are split into ``prefill_chunk``-token pieces consumed by
        # the SAME scan body as decode steps under a per-lane mode mask, so
        # a long prompt can never stall every running stream's next chunk
        # launch. The bucketed prefill program stays behind
        # ``fused_prefill=False`` as the bit-parity reference.
        self.fused_prefill = bool(fused_prefill)
        self.prefill_chunk = min(int(prefill_chunk), self.max_prompt_len)
        if self.fused_prefill and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if self.fused_prefill and disaggregate_prefill:
            raise ValueError(
                "fused_prefill folds prefill into the decode scan; "
                "disaggregate_prefill needs a standalone prefill program "
                "on its own device slice — the two are mutually exclusive")
        if self.fused_prefill and speculative and float(temperature) != 0.0:
            raise ValueError(
                "fused_prefill + speculative supports greedy sampling only "
                "(temperature=0): the fused scan body verifies drafts with "
                "the greedy rule")
        # one token budget per scan iteration shared by prompt chunks and
        # decode lanes — the scheduler fills admission against it. Default:
        # room for ~2 concurrent prompt chunks on top of a full decode
        # batch (prefill keeps flowing without ever monopolizing a step).
        if chunk_token_budget is not None:
            self.chunk_token_budget = int(chunk_token_budget)
        else:
            self.chunk_token_budget = 2 * self.prefill_chunk + self.max_batch
        if self.fused_prefill and self.chunk_token_budget < 1:
            raise ValueError(
                f"chunk_token_budget must be >= 1, got {chunk_token_budget}")
        # prompts at/above this length skip inline chunking and run one
        # sequence-parallel (Ulysses) bucketed prefill instead — sp shards
        # the long forward over the mesh's sp axis, then hands the finished
        # KV to decode. None disables the sp leg. At mesh sp=1 (CPU tests)
        # every sp constraint is the identity, so outputs stay bitwise
        # equal to the plain bucketed program.
        self.sp_prefill_threshold = (None if sp_prefill_threshold is None
                                     else int(sp_prefill_threshold))
        if prefill_buckets is None:
            self._buckets = default_prefill_buckets(self.max_prompt_len)
        else:
            self._buckets = sorted(
                {int(b) for b in prefill_buckets
                 if 0 < int(b) <= self.max_prompt_len}
                | {self.max_prompt_len})
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.speculative = bool(speculative)
        if self.speculative:
            from .speculative import NGramDrafter
            self.drafter = (drafter if drafter is not None
                            else NGramDrafter(spec_k, spec_ngram))
            self.spec_k = int(self.drafter.k)
        else:
            self.drafter = None
            self.spec_k = 0
        # speculative decode always runs the chunked scan program (the
        # verify forward is a multi-token apply; K=1 is a length-1 scan);
        # fused prefill lives inside that scan, so it forces it too
        self._chunked = (self.decode_chunk > 1 or self.speculative
                         or self.fused_prefill)

        self.paged = bool(paged)
        if self.paged:
            from .paged_kv import PagedKVCacheManager
            # prefix reuse replays a stored first token, which is only
            # faithful when sampling is deterministic — greedy only
            self.kv = PagedKVCacheManager(
                self.module, engine.params, self.max_batch,
                block_size=kv_block_size, num_blocks=kv_pool_blocks,
                prefix_cache_capacity=prefix_cache_capacity,
                prefix_caching=prefix_cache and self.temperature == 0.0)
        else:
            self.kv = SlotKVCacheManager(self.module, engine.params,
                                         self.max_batch)

        # ---- tiered KV (serving/kv_tiers.py) ----
        # Demote cold prefix entries HBM -> host DRAM -> NVMe instead of
        # evicting; promote back asynchronously on a later hit.
        self.kv_tier = None
        if tiered_kv:
            if not self.paged:
                raise ValueError(
                    "tiered_kv requires paged=True (demotion is "
                    "block-granular behind the paged allocator)")
            if not self.kv.prefix_enabled:
                raise ValueError(
                    "tiered_kv needs the prefix cache (prefix_cache="
                    "True and temperature=0): demotion operates on "
                    "prefix-cache entries")
            from .kv_tiers import KVTierManager
            self.kv_tier = KVTierManager(
                dram_bytes=int(tier_dram_bytes),
                nvme_bytes=tier_nvme_bytes,
                spill_dir=tier_spill_dir)
            self.kv.attach_tier(self.kv_tier)

        # ---- mesh placement: tp-sharded KV + disaggregated prefill ----
        # Which params each program family sees. Default: the inference
        # engine's own placement for both. Disaggregation re-places two
        # committed copies on disjoint device slices of the engine mesh.
        self._decode_params = engine.params
        self._prefill_params = engine.params
        self._handoff_sharding = None       # set in disaggregated mode
        head_dim = None
        if getattr(cfg, "num_heads", None):
            head_dim = int(cfg.d_model) // int(cfg.num_heads)
        self.disaggregated = bool(disaggregate_prefill)
        if self.disaggregated:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel import mesh as mesh_lib
            from ..runtime.sharding import ShardingRules, kv_shardings
            if getattr(engine, "quantized", False):
                raise ValueError(
                    "disaggregate_prefill with int8-quantized weights is "
                    "unsupported (two placements of the quantized tree)")
            devs = list(mesh.devices.flat)
            if len(devs) < 2:
                raise ValueError(
                    "disaggregate_prefill needs >= 2 devices (one decode "
                    "slice + one prefill slice)")
            half = len(devs) // 2
            dec_tp = self.tp if half % max(self.tp, 1) == 0 else 1
            dshape = mesh_lib.MeshShape.infer(half, tp=dec_tp)
            pshape = mesh_lib.MeshShape.infer(len(devs) - half, tp=dec_tp)
            self._decode_mesh = mesh_lib.build_mesh(dshape,
                                                    devices=devs[:half])
            self._prefill_mesh = mesh_lib.build_mesh(pshape,
                                                     devices=devs[half:])
            drules = ShardingRules(self._decode_mesh, zero_stage=0)
            prules = ShardingRules(self._prefill_mesh, zero_stage=0)
            self._decode_params = jax.device_put(
                engine.params,
                drules.shardings(drules.param_specs(engine.params)))
            self._prefill_params = jax.device_put(
                engine.params,
                prules.shardings(prules.param_specs(engine.params)))
            # prompt KV is born on the prefill slice and handed to the
            # decode slice replicated; the insert scatter then lands it in
            # the (possibly tp-sharded) pool rows
            self._handoff_sharding = NamedSharding(self._decode_mesh,
                                                   PartitionSpec())
            self.kv.update(jax.device_put(
                self.kv.cache,
                kv_shardings(self.kv.cache, self._decode_mesh,
                             head_dim=head_dim)))
        elif self.tp > 1:
            from ..runtime.sharding import kv_shardings
            # commit the fresh arena/pool with its tp NamedShardings so
            # the first insert/decode never sees an unplaced cache
            self.kv.update(jax.device_put(
                self.kv.cache,
                kv_shardings(self.kv.cache, mesh, head_dim=head_dim)))

        self.scheduler = ContinuousBatchScheduler(
            self.kv.allocator, max_queue=max_queue,
            max_prompt_len=self.max_prompt_len)
        self.metrics = ServingMetrics(monitor,
                                      emit_every_steps=emit_every_steps)
        self._rng = jax.random.PRNGKey(seed)
        self._last_token = np.zeros(self.max_batch, np.int32)
        # distinct (batch, bucket[, "sp"]) prefill shapes seen so far —
        # the compile count ServingMetrics reports
        self._prefill_shapes: Set[Tuple] = set()
        # host corrections to device-carried chunk state, applied at the
        # NEXT chunk launch (see _device_state)
        self._deact_slots: Set[int] = set()
        self._admit_patches: Dict[int, Tuple] = {}
        # fused-prefill host mirrors (slot-keyed, fused mode only).
        # Prompt-chunk consumption is DETERMINISTIC (a prefilling lane
        # can't EOS or exhaust its budget), so the host tracks it with two
        # cursors instead of syncing device state: _pf_consumed advances
        # at chunk CONSUME (authoritative — scheduler-facing state),
        # _pf_launched advances at chunk LAUNCH (the speculative horizon
        # the next prompt_buf is built from, one chunk ahead under the
        # double-buffered loop).
        self._pf_consumed: Dict[int, int] = {}
        self._pf_launched: Dict[int, int] = {}
        # slots whose token #1 has not been emitted yet: the first valid
        # token routes through scheduler.record_first_token (TTFT stamp,
        # no allocator advance), the rest through step_tokens_chunk
        self._pf_first_pending: Set[int] = set()
        # paged MISS admission plans deferred to first-token time: the
        # prefix-cache commit needs the sampled token #1, which the fused
        # path only learns when the completing chunk retires
        self._pf_plans: Dict[int, Any] = {}
        # prompt tokens consumed inside the decode scan (the fused
        # analogue of serve/prefill_tokens) — the frontend throughput
        # estimator folds this into its one-EWMA budget rate
        self.inline_prefill_tokens = 0
        # the at-most-one in-flight chunk of the double-buffered loop
        # (run()'s pipelined drain and external pump() drivers share it)
        self._pending: Optional[_InflightChunk] = None
        # crash flight recorder (telemetry.flight_recorder), attached by
        # the owning ServingFrontend; engine-side records are host-only
        # deque appends — no device work, no retrace surface
        self.flight = None
        # chunk-timeline profiler (telemetry.profiler.ChunkProfiler),
        # attached externally the same way; every hook site is guarded by
        # a None check so the detached cost is one attribute load, and
        # the hooks themselves are perf_counter stamps + deque appends —
        # no device work, no retrace surface
        self.profiler = None

        mat = engine._materialize
        module = self.module
        temperature_, top_k_ = self.temperature, self.top_k
        top_p_ = self.top_p
        # megakernel: every sampler call in the compiled programs routes
        # through the fused Pallas epilogue (unsupported vocab shapes
        # fall back to the reference INSIDE the router, so the program
        # never forks on shape), and the speculative verifier filters
        # with the same fused kernel
        sample_ = fused_sample_tokens if self.megakernel else sample_tokens
        spec_filter_ = fused_filter_logits if self.megakernel else None
        max_seq_ = self.max_seq_len
        B_ = self.max_batch
        spec_k_ = self.spec_k
        drafter_ = self.drafter
        K = self.decode_chunk
        C_ = self.prefill_chunk

        def prefill(params, ids, true_lens, rng):
            pm = mat(params)
            positions = jnp.arange(ids.shape[1])[None, :]
            logits, vc = module.apply({"params": pm}, ids,
                                      positions=positions, mutable=["cache"])
            if isinstance(logits, tuple):
                logits = logits[0]
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]  # [n,V]
            tok = sample_(last, rng, temperature_, top_k_, top_p_)
            return tok, vc["cache"]

        # sequence-parallel (Ulysses) prefill for very long prompts: the
        # same bucketed program shape, but the module constrains q/k/v
        # head-sharded over the mesh's sp axis so the one long forward
        # spreads across chips before its KV is handed to decode. The
        # einsum paths are forced (the pallas custom calls don't
        # auto-partition under GSPMD); at sp=1 every constraint is the
        # identity, so outputs are bitwise equal to ``prefill``.
        sp_module = None
        if self.sp_prefill_threshold is not None:
            sp_cfg = dataclasses.replace(
                self.module.cfg, sequence_parallel=True,
                cp_impl="ulysses", attention_impl="xla",
                decode_impl="xla")
            sp_module = type(self.module)(sp_cfg)
        self._sp_module = sp_module

        def prefill_sp(params, ids, true_lens, rng):
            pm = mat(params)
            positions = jnp.arange(ids.shape[1])[None, :]
            logits, vc = sp_module.apply({"params": pm}, ids,
                                         positions=positions,
                                         mutable=["cache"])
            if isinstance(logits, tuple):
                logits = logits[0]
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            tok = sample_(last, rng, temperature_, top_k_, top_p_)
            return tok, vc["cache"]

        def decode(params, cache, tokens, positions, rng):
            pm = mat(params)
            # pin the write cursor exactly like the chunk program: idle
            # lanes carry the max_seq sentinel (positions from
            # _decode_once), so a paged lane's stale block table can never
            # route a speculative write into a re-leased block
            cache = _with_write_index(cache, positions)
            logits, vc = module.apply(
                {"params": pm, "cache": cache}, tokens[:, None],
                positions=positions[:, None], mutable=["cache"])
            if isinstance(logits, tuple):
                logits = logits[0]
            tok = sample_(logits[:, -1], rng, temperature_, top_k_,
                          top_p_)
            return tok, vc["cache"]

        def _with_write_index(cache, write_pos):
            # the engine owns the per-slot write cursor: overwrite every
            # cache_index leaf with this step's write positions (retired
            # lanes carry the max_seq sentinel -> models/gpt.py drops the
            # write entirely)
            def leaf(path, x):
                if "cache_index" in jax.tree_util.keystr(path):
                    return jnp.broadcast_to(
                        write_pos.astype(x.dtype), x.shape)
                return x
            return jax.tree_util.tree_map_with_path(leaf, cache)

        def decode_chunk_fn(params, cache, tokens, positions, active,
                            eos, remaining, rng):
            pm = mat(params)

            def body(carry, _):
                c, tok, pos, act, rem, key = carry
                write_pos = jnp.where(act, pos,
                                      jnp.int32(max_seq_))  # masked lanes
                c = _with_write_index(c, write_pos)
                logits, vc = module.apply(
                    {"params": pm, "cache": c}, tok[:, None],
                    positions=pos[:, None], mutable=["cache"])
                if isinstance(logits, tuple):
                    logits = logits[0]
                key, sub = jax.random.split(key)
                nxt = sample_(logits[:, -1], sub,
                              temperature_, top_k_, top_p_)
                nxt = jnp.where(act, nxt, tok)       # frozen lanes hold
                emitted = act                        # validity of nxt
                rem = jnp.where(act, rem - 1, rem)
                hit_eos = jnp.logical_and(eos >= 0, nxt == eos)
                act = jnp.logical_and(
                    act, jnp.logical_and(rem > 0,
                                         jnp.logical_not(hit_eos)))
                pos = jnp.where(emitted, pos + 1, pos)
                return (vc["cache"], nxt, pos, act, rem, key), (nxt, emitted)

            (c, tok_f, pos_f, act_f, rem_f, _), (toks, valid) = jax.lax.scan(
                body, (cache, tokens, positions, active, remaining, rng),
                None, length=K)
            return (jnp.moveaxis(toks, 0, 1), jnp.moveaxis(valid, 0, 1),
                    c, tok_f, pos_f, act_f, rem_f)

        def decode_chunk_spec_fn(params, cache, tokens, positions, active,
                                 eos, remaining, hist, rng):
            """Speculative chunk: each scan step drafts k tokens per lane
            (drafter gathers over the device-resident [B, S] history),
            scores all k+1 positions in ONE target forward, and emits the
            accepted prefix + correction token — up to k+1 tokens per lane
            per step, with exactly the sampler's distribution (greedy:
            bit-identical to the sequential loop; see
            serving/speculative.py for the argument). The per-lane
            accepted length n advances the write cursor and positions;
            KV rows written for rejected drafts sit ABOVE the new fill,
            so they are dead (masked by every later read) until a later
            step overwrites them."""
            from .speculative import verify_greedy, verify_rejection
            pm = mat(params)
            kp1 = spec_k_ + 1
            rows = jnp.arange(B_, dtype=jnp.int32)
            j = jnp.arange(kp1, dtype=jnp.int32)[None, :]

            def body(carry, _):
                c, tok, pos, act, rem, key, h = carry
                # keep the invariant hist[b, pos[b]] == tok[b] (idempotent
                # after the first step; fresh admits are patched by the
                # host, this covers the launch-time carry)
                h = h.at[rows, jnp.where(act, pos, jnp.int32(max_seq_))
                         ].set(tok, mode="drop")
                drafts = drafter_.propose(h, tok, pos)          # [B, k]
                inputs = jnp.concatenate([tok[:, None], drafts], axis=1)
                write_pos = jnp.where(act, pos, jnp.int32(max_seq_))
                c = _with_write_index(c, write_pos)
                qpos = pos[:, None] + j
                logits, vc = module.apply(
                    {"params": pm, "cache": c}, inputs,
                    positions=qpos, mutable=["cache"])
                if isinstance(logits, tuple):
                    logits = logits[0]                          # [B,k+1,V]
                if temperature_ == 0.0:
                    emitted, acc = verify_greedy(logits, drafts)
                    key_n = key
                else:
                    key_n, sub = jax.random.split(key)
                    emitted, acc = verify_rejection(
                        logits, drafts, sub, temperature_, top_k_, top_p_,
                        filter_fn=spec_filter_)
                # candidate validity: live lane, within the accepted
                # prefix (+ the correction/bonus at j == acc), within the
                # remaining token budget
                cand = act[:, None] & (j <= acc[:, None]) & \
                    (j < rem[:, None])
                hit = (eos[:, None] >= 0) & (emitted == eos[:, None])
                cut = (cand & hit).astype(jnp.int32)
                prior_hits = jnp.cumsum(cut, axis=1) - cut
                valid = cand & (prior_hits == 0)    # stop AFTER first EOS
                n = jnp.sum(valid.astype(jnp.int32), axis=1)    # [B]
                last = jnp.take_along_axis(
                    emitted, jnp.clip(n - 1, 0, spec_k_)[:, None],
                    axis=1)[:, 0]
                tok_n = jnp.where(n > 0, last, tok)
                stopped = jnp.any(valid & hit, axis=1)
                rem_n = rem - n
                act_n = act & (rem_n > 0) & jnp.logical_not(stopped)
                # emitted token j landed at history index pos + 1 + j
                widx = jnp.where(valid, pos[:, None] + 1 + j,
                                 jnp.int32(max_seq_))
                h = h.at[rows[:, None], widx].set(emitted, mode="drop")
                pos_n = pos + n
                return ((vc["cache"], tok_n, pos_n, act_n, rem_n, key_n, h),
                        (emitted, valid))

            (c, tok_f, pos_f, act_f, rem_f, _, hist_f), (toks, valid) = \
                jax.lax.scan(
                    body,
                    (cache, tokens, positions, active, remaining, rng,
                     hist),
                    None, length=K)
            toks = jnp.moveaxis(toks, 0, 1).reshape(B_, K * kp1)
            valid = jnp.moveaxis(valid, 0, 1).reshape(B_, K * kp1)
            return (toks, valid, c, tok_f, pos_f, act_f, rem_f, hist_f)

        def decode_chunk_fused_fn(params, cache, tokens, positions, active,
                                  eos, remaining, pf_rem, prompt_buf, rng):
            """Fused chunked-prefill decode scan (the Sarathi-Serve /
            vLLM chunked-prefill idea, in-scan): each scan step a live
            lane either consumes its next <= C prompt tokens (prefill
            mode — incremental KV append, nothing emitted until the
            completing chunk samples token #1) or emits one decode token.
            ONE C-wide forward serves both modes under the per-lane mode
            mask ``pf_rem > 0``; decode lanes broadcast their last token
            across the C columns and sample at column 0. ``prompt_buf``
            [K, B, C] carries each prefilling lane's next K*C prompt
            tokens (zeros elsewhere — the host builds it per launch).

            Write-cursor discipline is unchanged: pad columns write KV
            ABOVE the lane's logical fill (or through the paged table's
            sentinel rows), where every causal read masks them until a
            later step legitimately overwrites — the same argument that
            covers the speculative verify's rejected-draft rows. Greedy
            outputs are bitwise identical to bucketed prefill + decode
            because both run the same masked cache attention per
            position (tests/test_fused_prefill.py)."""
            pm = mat(params)
            cspan = jnp.arange(C_, dtype=jnp.int32)[None, :]

            def body(carry, pchunk):
                c, tok, pos, act, rem, pf, key = carry
                is_pf = jnp.logical_and(act, pf > 0)
                n_cons = jnp.where(is_pf, jnp.minimum(pf, C_), 0)
                completing = jnp.logical_and(is_pf, pf <= C_)
                inputs = jnp.where(is_pf[:, None], pchunk, tok[:, None])
                qpos = pos[:, None] + cspan
                write_pos = jnp.where(act, pos, jnp.int32(max_seq_))
                c = _with_write_index(c, write_pos)
                logits, vc = module.apply(
                    {"params": pm, "cache": c}, inputs,
                    positions=qpos, mutable=["cache"])
                if isinstance(logits, tuple):
                    logits = logits[0]                      # [B, C, V]
                key, sub = jax.random.split(key)
                # sample at the lane's LAST real column: n_cons-1 for a
                # completing prefill lane (token #1), 0 for decode lanes
                sel = jnp.where(is_pf, jnp.maximum(n_cons - 1, 0), 0)
                last = jnp.take_along_axis(
                    logits, sel[:, None, None], axis=1)[:, 0]   # [B, V]
                nxt = sample_(last, sub, temperature_, top_k_,
                              top_p_)
                emits = jnp.logical_and(
                    act, jnp.logical_or(completing,
                                        jnp.logical_not(is_pf)))
                nxt = jnp.where(emits, nxt, tok)
                rem = jnp.where(emits, rem - 1, rem)
                hit_eos = (eos >= 0) & (nxt == eos) & emits
                act = jnp.logical_and(
                    act, jnp.where(emits,
                                   (rem > 0) & jnp.logical_not(hit_eos),
                                   True))
                pos = pos + jnp.where(is_pf, n_cons,
                                      jnp.where(emits, 1, 0))
                pf = pf - n_cons
                return ((vc["cache"], nxt, pos, act, rem, pf, key),
                        (nxt, emits))

            (c, tok_f, pos_f, act_f, rem_f, pf_f, _), (toks, valid) = \
                jax.lax.scan(
                    body,
                    (cache, tokens, positions, active, remaining, pf_rem,
                     rng),
                    prompt_buf)
            return (jnp.moveaxis(toks, 0, 1), jnp.moveaxis(valid, 0, 1),
                    c, tok_f, pos_f, act_f, rem_f, pf_f)

        def decode_chunk_fused_spec_fn(params, cache, tokens, positions,
                                       active, eos, remaining, pf_rem,
                                       prompt_buf, hist, rng):
            """Fused chunked prefill + speculative decode (greedy only —
            enforced at construction). Step width is W = max(C, k+1):
            prefill-mode lanes consume their next prompt chunk through
            the first C columns; decode-mode lanes verify k drafts
            through the first k+1. A completing prefill lane emits token
            #1 at ys column 0; the host excludes prefill-mode steps from
            acceptance accounting via its own deterministic replay of
            the pf cursor (engine._sim_chunk_prefill)."""
            from .speculative import verify_greedy
            pm = mat(params)
            kp1 = spec_k_ + 1
            W = max(C_, kp1)
            rows = jnp.arange(B_, dtype=jnp.int32)
            j = jnp.arange(kp1, dtype=jnp.int32)[None, :]
            wspan = jnp.arange(W, dtype=jnp.int32)[None, :]

            def body(carry, pchunk):
                c, tok, pos, act, rem, pf, key, h = carry
                is_pf = jnp.logical_and(act, pf > 0)
                n_cons = jnp.where(is_pf, jnp.minimum(pf, C_), 0)
                completing = jnp.logical_and(is_pf, pf <= C_)
                is_dec = jnp.logical_and(act, jnp.logical_not(is_pf))
                # hist invariant h[b, pos] == tok for DECODE lanes only —
                # a prefilling lane's row already holds its prompt at
                # [0, L), and pos points inside it
                h = h.at[rows, jnp.where(is_dec, pos, jnp.int32(max_seq_))
                         ].set(tok, mode="drop")
                drafts = drafter_.propose(h, tok, pos)          # [B, k]
                dec_in = jnp.concatenate([tok[:, None], drafts], axis=1)
                if W > kp1:
                    dec_in = jnp.pad(dec_in, ((0, 0), (0, W - kp1)))
                pf_in = pchunk
                if W > C_:
                    pf_in = jnp.pad(pf_in, ((0, 0), (0, W - C_)))
                inputs = jnp.where(is_pf[:, None], pf_in, dec_in)
                write_pos = jnp.where(act, pos, jnp.int32(max_seq_))
                c = _with_write_index(c, write_pos)
                qpos = pos[:, None] + wspan
                logits, vc = module.apply(
                    {"params": pm, "cache": c}, inputs,
                    positions=qpos, mutable=["cache"])
                if isinstance(logits, tuple):
                    logits = logits[0]                      # [B, W, V]
                # ---- decode lanes: greedy verify over the first k+1 ----
                emitted, acc = verify_greedy(logits[:, :kp1], drafts)
                cand = is_dec[:, None] & (j <= acc[:, None]) & \
                    (j < rem[:, None])
                hitv = (eos[:, None] >= 0) & (emitted == eos[:, None])
                cut = (cand & hitv).astype(jnp.int32)
                prior_hits = jnp.cumsum(cut, axis=1) - cut
                dvalid = cand & (prior_hits == 0)
                n = jnp.sum(dvalid.astype(jnp.int32), axis=1)   # [B]
                last = jnp.take_along_axis(
                    emitted, jnp.clip(n - 1, 0, spec_k_)[:, None],
                    axis=1)[:, 0]
                # ---- prefill lanes: token #1 at column n_cons - 1 ----
                sel = jnp.maximum(n_cons - 1, 0)
                t1 = jnp.argmax(jnp.take_along_axis(
                    logits, sel[:, None, None], axis=1)[:, 0],
                    axis=-1).astype(jnp.int32)
                pf_emit = jnp.logical_and(act, completing)
                t1_eos = (eos >= 0) & (t1 == eos) & pf_emit
                # ---- merge the two modes' carries ----
                tok_n = jnp.where(is_pf, jnp.where(pf_emit, t1, tok),
                                  jnp.where(n > 0, last, tok))
                stopped = jnp.any(dvalid & hitv, axis=1) | t1_eos
                n_all = jnp.where(is_pf, pf_emit.astype(jnp.int32), n)
                rem_n = rem - n_all
                act_n = act & jnp.where(
                    jnp.logical_and(is_pf, jnp.logical_not(pf_emit)),
                    True, (rem_n > 0) & jnp.logical_not(stopped))
                # ys fixed at width W: decode lanes at columns 0..k, a
                # completing prefill lane's token #1 at column 0
                ys_tok = jnp.where(is_pf[:, None],
                                   jnp.broadcast_to(t1[:, None],
                                                    (B_, kp1)), emitted)
                ys_val = jnp.where(is_pf[:, None],
                                   pf_emit[:, None] & (j == 0), dvalid)
                if W > kp1:
                    ys_tok = jnp.pad(ys_tok, ((0, 0), (0, W - kp1)))
                    ys_val = jnp.pad(ys_val, ((0, 0), (0, W - kp1)))
                # history: decode-lane token j landed at pos + 1 + j;
                # a completing lane's token #1 at index prompt_len
                widx = jnp.where(dvalid, pos[:, None] + 1 + j,
                                 jnp.int32(max_seq_))
                h = h.at[rows[:, None], widx].set(emitted, mode="drop")
                h = h.at[rows, jnp.where(pf_emit, pos + n_cons,
                                         jnp.int32(max_seq_))
                         ].set(t1, mode="drop")
                pos_n = pos + jnp.where(is_pf, n_cons, n)
                pf_n = pf - n_cons
                return ((vc["cache"], tok_n, pos_n, act_n, rem_n, pf_n,
                         key, h), (ys_tok, ys_val))

            (c, tok_f, pos_f, act_f, rem_f, pf_f, _, hist_f), \
                (toks, valid) = jax.lax.scan(
                    body,
                    (cache, tokens, positions, active, remaining, pf_rem,
                     rng, hist),
                    prompt_buf)
            toks = jnp.moveaxis(toks, 0, 1).reshape(B_, K * W)
            valid = jnp.moveaxis(valid, 0, 1).reshape(B_, K * W)
            return (toks, valid, c, tok_f, pos_f, act_f, rem_f, pf_f,
                    hist_f)

        # prefill retraces lazily per (n, bucket) shape — the jit cache IS
        # the bucket program table
        self._jit_prefill = jax.jit(prefill)
        # the sp prefill is its own program family ("prefill_sp_fn"),
        # bucket-lazy exactly like the plain prefill
        if sp_module is not None:
            prefill_sp.__name__ = "prefill_sp_fn"
            self._jit_prefill_sp = jax.jit(prefill_sp)
        else:
            self._jit_prefill_sp = None
        # donate the arena: XLA updates every slot's KV rows in place
        self._jit_decode = jax.jit(decode, donate_argnums=(1,))
        # distinct function name => distinct TraceAuditor budget: every
        # fused / spec / int8 / paged combination is a different compiled
        # program family whose retrace count is pinned separately
        # ("decode_chunk" + "_megakernel"? + "_fused"? + "_spec"? +
        # "_int8"? + "_paged"? + "_fn")
        variant = "decode_chunk"
        if self.megakernel:
            variant += "_megakernel"
        if self.fused_prefill:
            variant += "_fused"
        if self.speculative:
            variant += "_spec"
        if self.kv_dtype == "int8":
            variant += "_int8"
        if self.paged:
            variant += "_paged"
        # tp-sharded and disaggregated engines compile against different
        # placement metadata, so they are their own program families with
        # their own pinned budgets — the dense/paged budgets stay exact
        if self.tp > 1:
            variant += f"_tp{self.tp}"
        if self.disaggregated:
            variant += "_disagg"
        variant += "_fn"
        if self.fused_prefill:
            chunk_fn = (decode_chunk_fused_spec_fn if self.speculative
                        else decode_chunk_fused_fn)
        else:
            chunk_fn = (decode_chunk_spec_fn if self.speculative
                        else decode_chunk_fn)
        chunk_fn.__name__ = variant
        self._jit_decode_chunk = jax.jit(chunk_fn, donate_argnums=(1,))
        # arena-size gauges at init: the KV footprint is fixed for the
        # engine's lifetime, headroom varies (re-gauged per chunk)
        arena = self.kv.arena_report()
        telemetry.gauge("serve/arena_bytes", float(arena["arena_bytes"]))
        telemetry.gauge("serve/arena_headroom_bytes",
                        float(arena["headroom_bytes"]))
        # int8 KV: bytes the quantized arena saves vs the fp layout it
        # replaces (0.0 in fp mode — the gauge is always present so
        # dashboards need no mode branch)
        telemetry.gauge("serve/kv_bytes_saved",
                        float(arena.get("kv_bytes_saved", 0.0)))
        if self.paged:
            self._bytes_per_block = arena["bytes_per_block"]
            self._gauge_block_pool()
        else:
            self._arena_bytes_per_slot = arena["bytes_per_slot"]
        log_dist(f"serving engine ready: slots={self.max_batch} "
                 f"prefill_buckets={self._buckets} "
                 f"decode_chunk={self.decode_chunk} "
                 f"max_seq={max_seq} "
                 f"kv={'paged' if self.paged else 'dense'} "
                 f"tp={self.tp} "
                 f"disaggregated={self.disaggregated}", ranks=[0])

    # --------------------------------------------------------------- API
    def submit(self, prompt: Union[Request, Sequence[int], np.ndarray],
               **request_kwargs) -> Request:
        """Enqueue one request (token-id prompt or a prebuilt Request).
        Rejections (bounded queue, oversized prompt) come back as
        ``status == "rejected"`` with ``reject_reason`` set — the
        backpressure signal, not an exception."""
        req = prompt if isinstance(prompt, Request) else Request(
            prompt=np.asarray(prompt, np.int32), **request_kwargs)
        self.metrics.start()
        if not self.scheduler.submit(req):
            self.metrics.on_rejected()
        return req

    def cancel(self, req: Request) -> bool:
        """Caller-initiated termination: a queued request never prefills;
        a running one frees its slot immediately (host side) and its
        device lane is deactivated at the NEXT chunk launch through the
        host-event patch path (``_deact_slots``), so at most K-1 tokens of
        speculative device work are wasted — and none are delivered,
        because the launch-time slot->uid snapshot drops tokens from
        retired occupants. Returns False if the request was already
        terminal."""
        slot = req.slot if req.status == "running" else None
        cancelled = self.scheduler.cancel(req)
        if cancelled and slot is not None:
            self._deact_slots.add(slot)
            self._admit_patches.pop(slot, None)
            self._clear_pf_slot(slot)
        return cancelled

    def _clear_pf_slot(self, slot: int) -> None:
        """Drop a slot's fused-prefill mirrors (lane retired or admitted
        through a non-inline path). An uncommitted paged MISS plan also
        releases its duplicate-prompt hold so an identical prompt can
        admit again."""
        self._pf_consumed.pop(slot, None)
        self._pf_launched.pop(slot, None)
        self._pf_first_pending.discard(slot)
        plan = self._pf_plans.pop(slot, None)
        if plan is not None:
            self.kv.abandon_plan(plan)

    # ------------------------------------------------- live migration
    def can_migrate(self, req: Request) -> bool:
        """Is ``req`` movable right now? Paged KV only (blocks are the
        portable unit), tp=1 (a sharded pool's leaves live on a mesh this
        bundle format doesn't describe), running with at least one
        emitted token, and fully prefilled — a mid-prompt fused lane's KV
        is still being written by the scan."""
        if not self.paged or self.tp > 1 or self.disaggregated:
            return False
        if req.status != "running" or not req.tokens:
            return False
        slot = req.slot
        if slot is None or self.scheduler.running.get(slot) is not req:
            return False
        if self.fused_prefill and self._pf_consumed.get(
                slot, req.prompt_len) < req.prompt_len:
            return False
        return True

    def export_request(self, req: Request) -> Dict[str, Any]:
        """Serialize a RUNNING request's full decode state: KV blocks
        (in table order, written blocks only), the decode cursor, and
        the request identity — the bundle ``import_request`` re-homes on
        another engine. Consistency argument: at a chunk boundary
        ``fill == prompt_len + len(tokens) - 1`` and the last token's KV
        row is NOT yet written (it is written when the token is fed), so
        rows ``[0, fill)`` are final even with the next chunk in flight —
        that chunk only writes at/above ``fill``, and gathering the
        post-chunk pool syncs after those writes land harmlessly in rows
        the importer masks (its write cursor starts at ``fill``). Does
        NOT cancel ``req`` — the caller re-homes first, then cancels."""
        if not self.can_migrate(req):
            raise MigrationError(
                f"request uid={req.uid} is not migratable "
                f"(status={req.status!r}, paged={self.paged}, "
                f"tp={self.tp})")
        slot = req.slot
        fill = req.prompt_len + len(req.tokens) - 1
        have = int(self.kv.fill[slot])
        if have != fill:
            raise MigrationError(
                f"slot {slot} fill {have} != expected {fill} "
                f"(chunk boundary invariant violated)")
        bs = self.kv.allocator.block_size
        n_blocks = max(1, -(-fill // bs))
        leaves = self.kv.export_blocks(slot, n_blocks)
        kv_bytes = sum(int(a.nbytes) for a in leaves.values())
        telemetry.instant("serve/migrate_export", uid=req.uid,
                          slot=slot, n_blocks=n_blocks, bytes=kv_bytes)
        if self.flight is not None:
            self.flight.record("migrate_export", uid=req.uid, slot=slot,
                               n_blocks=n_blocks, bytes=kv_bytes)
        return {
            "schema": MIGRATE_SCHEMA,
            "prompt": [int(t) for t in np.asarray(req.prompt)],
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": (None if req.eos_token_id is None
                             else int(req.eos_token_id)),
            "deadline_s": (None if req.deadline_s is None
                           else float(req.deadline_s)),
            "tenant": req.tenant,
            "trace_id": req.trace_id,
            "fill": int(fill),
            "block_size": int(bs),
            "n_blocks": int(n_blocks),
            "kv_bytes": int(kv_bytes),
            "kv": leaves,
        }

    def import_request(self, bundle: Dict[str, Any]) -> Request:
        """Re-home an exported request: lease a slot + its full block
        reservation (``alloc_span``), scatter the shipped blocks, and
        join the running set mid-decode — the next chunk feeds the
        carried last token at position ``fill``, exactly as the source
        engine would have. Raises :class:`MigrationError` when this
        engine cannot host it (layout mismatch, pool OOM); the caller
        re-imports at the source or fails the stream structurally."""
        if not self.paged or self.tp > 1 or self.disaggregated:
            raise MigrationError(
                "import_request needs a paged, unsharded engine")
        if bundle.get("schema") != MIGRATE_SCHEMA:
            raise MigrationError(
                f"unknown migration schema {bundle.get('schema')!r}")
        bs = self.kv.allocator.block_size
        if int(bundle["block_size"]) != bs:
            raise MigrationError(
                f"block_size mismatch: bundle {bundle['block_size']} "
                f"vs engine {bs}")
        prompt = np.asarray(bundle["prompt"], np.int32)
        tokens = [int(t) for t in bundle["tokens"]]
        fill = int(bundle["fill"])
        max_new = int(bundle["max_new_tokens"])
        if fill != prompt.shape[0] + len(tokens) - 1:
            raise MigrationError(
                f"bundle cursor fill={fill} inconsistent with "
                f"prompt_len={prompt.shape[0]} + {len(tokens)} tokens")
        if fill + 1 > self.max_seq_len:
            raise MigrationError(
                f"sequence length {fill + 1} exceeds this engine's "
                f"max_seq_len {self.max_seq_len}")
        n_lease = min(-(-(prompt.shape[0] + max_new) // bs),
                      self.kv.allocator.blocks_per_seq)
        if n_lease < int(bundle["n_blocks"]):
            raise MigrationError(
                f"lease of {n_lease} blocks cannot hold the bundle's "
                f"{bundle['n_blocks']} written blocks")
        slot = self.kv.allocator.alloc_span(fill, n_lease)
        if slot is None:
            raise MigrationError(
                "kv_blocks_exhausted: no slot/blocks for the incoming "
                "request")
        try:
            self.kv.import_blocks(slot, bundle["kv"])
        except Exception:
            self.kv.allocator.free(slot)
            raise
        req = Request(
            prompt=prompt, max_new_tokens=max_new,
            eos_token_id=bundle.get("eos_token_id"),
            deadline_s=bundle.get("deadline_s"),
            trace_id=bundle.get("trace_id"),
            tenant=bundle.get("tenant") or "default")
        now = self.scheduler.clock()
        req.submit_t = now
        req.first_token_t = now
        req.status = "running"
        req.slot = slot
        req.tokens = tokens
        self.scheduler.running[slot] = req
        self._last_token[slot] = tokens[-1]
        if self._chunked:
            if self.fused_prefill:
                self._clear_pf_slot(slot)
            rem = min(max_new - len(tokens),
                      self.kv.allocator.remaining(slot))
            eos = -1 if req.eos_token_id is None else int(req.eos_token_id)
            # admit-style patch, but the lane resumes at the migrated
            # cursor (pos = fill, not prompt_len): the carried last
            # token's KV row is written by the lane's first step here
            patch = (tokens[-1], fill, rem, eos)
            if self.fused_prefill:
                patch = patch + (0,)        # pf_rem: fully prefilled
            if self.speculative:
                patch = patch + (self._history_row(req),)
            self._admit_patches[slot] = patch
            self._deact_slots.discard(slot)
        telemetry.instant("serve/migrate_import", uid=req.uid,
                          slot=slot, fill=fill,
                          n_blocks=int(bundle["n_blocks"]))
        if self.flight is not None:
            self.flight.record("migrate_import", uid=req.uid, slot=slot,
                               fill=fill, tenant=req.tenant)
        self._gauge_block_pool()
        return req

    def pump(self) -> List[Request]:
        """One iteration of the double-buffered serve loop for EXTERNAL
        drivers (the serving frontend's engine thread): admit, keep one
        chunk in flight, and return every request that reached a terminal
        state during the call. Unlike ``step()`` this does not force a
        launch+sync pair per call — the in-flight chunk carries over
        between calls, so an external driver gets the same device-paced
        overlap ``run()`` has. Call until ``has_work()`` is False AND the
        last call returned with nothing in flight to drain completely."""
        before = len(self.scheduler.finished)
        if not self._chunked:
            self._admit()
            self._decode_once()
            return self.scheduler.finished[before:]
        if self._pending is None:
            self._admit()
            if self.scheduler.running:
                self._pending = self._launch_chunk(self._host_state())
            return self.scheduler.finished[before:]
        nxt = None
        if self._may_outlive_chunk():
            nxt = self._launch_chunk(self._device_state(self._pending))
        self._consume_chunk(self._pending)
        self._admit()
        self._pending = nxt
        return self.scheduler.finished[before:]

    @property
    def chunk_in_flight(self) -> bool:
        """True while a launched decode chunk has not been consumed —
        drain loops must keep pumping until this clears even after the
        scheduler reports no work."""
        return self._pending is not None

    def step(self) -> List[Request]:
        """One synchronous continuous-batching iteration: admit
        newly-runnable requests into free slots (bucketed batched prefill
        + arena insert), then one decode invocation over all live slots —
        a single fused step when ``decode_chunk == 1``, a K-step
        device-resident chunk otherwise. Returns requests finished this
        iteration."""
        before = len(self.scheduler.finished)
        self._admit()
        if not self._chunked:
            self._decode_once()
        elif self.scheduler.running:
            self._consume_chunk(self._launch_chunk(self._host_state()))
        return self.scheduler.finished[before:]

    def run(self, prompts: Optional[Sequence] = None,
            **request_kwargs) -> List[Request]:
        """Serve until drained. ``prompts``: token-id sequences (or Request
        objects) submitted up front; per-request kwargs (max_new_tokens,
        eos_token_id, deadline_s) apply to all of them. With
        ``decode_chunk > 1`` the loop is double-buffered: the next chunk
        is enqueued from device-resident carry state before the previous
        chunk's token buffer is synced. Returns the submitted requests in
        submission order (rejected ones included, flagged by status)."""
        submitted = [self.submit(p, **request_kwargs)
                     for p in (prompts or [])]
        if not self._chunked:
            while self.scheduler.has_work():
                self.step()
        else:
            self._serve_pipelined()
        self.metrics.maybe_emit(self.scheduler.queue_depth,
                                self.kv.occupancy, force=True)
        return submitted

    def estimate_chunk_cost(self) -> Optional[Dict[str, Any]]:
        """XLA cost analysis of one decode-chunk program invocation, for
        MFU reporting (telemetry.mfu). Lowers ``_jit_decode_chunk`` with
        abstract ``ShapeDtypeStruct`` args — no device buffers touched —
        but pays ONE extra XLA compile, so benches call this strictly
        AFTER their timed/audited passes (the pinned decode retrace
        budget stays exact; see docs/observability.md).

        XLA counts the chunk's ``lax.scan`` body once, not K times, so
        ``flops_per_chunk`` scales the program count by K — an estimate,
        flagged as such in the result. Returns None when the backend
        reports no costs."""
        import jax
        from ..telemetry import mfu as _mfu

        def abst(x):
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype)

        B = self.max_batch
        i32 = jax.ShapeDtypeStruct((B,), np.int32)
        chunk_args = [
            jax.tree.map(abst, self.engine.params),
            jax.tree.map(abst, self.kv.cache),
            i32, i32, jax.ShapeDtypeStruct((B,), bool), i32, i32]
        if self.fused_prefill:
            chunk_args.append(i32)    # pf_rem
            chunk_args.append(jax.ShapeDtypeStruct(
                (self.decode_chunk, B, self.prefill_chunk), np.int32))
        if self.speculative:
            chunk_args.append(
                jax.ShapeDtypeStruct((B, self.max_seq_len), np.int32))
        chunk_args.append(abst(self._rng))
        ca = _mfu.compiled_cost_analysis(
            self._jit_decode_chunk, *chunk_args)
        if ca is None:
            return None
        K = self.decode_chunk
        # each spec step scores spec_k + 1 positions in the one target
        # forward, so the per-position flop denominator scales with k+1
        per_step = (self.spec_k + 1) if self.speculative else 1
        flops_per_chunk = ca["flops"] * K
        return {
            "program_flops": ca["flops"],
            "bytes_accessed": ca["bytes_accessed"],
            "scan_length": K,
            "flops_per_chunk": flops_per_chunk,
            "flops_per_token": flops_per_chunk / (B * K * per_step),
            "max_batch": B,
            "scan_body_counted_once": True,
            "peak_flops_per_device": _mfu.peak_flops_per_device(),
        }

    def estimate_hbm(self) -> Optional[Dict[str, Any]]:
        """XLA memory analysis of the engine's own compiled programs
        (telemetry.memory) plus arena accounting and a live-buffer
        census — the ``hbm`` block in ``BENCH_serving.json``.

        Same discipline as :meth:`estimate_chunk_cost`: abstract
        lowering does not grow the audited jit cache (the pinned
        ``decode_chunk_fn == 3`` budget stays exact) but pays one extra
        XLA compile per analyzed program, so benches call this strictly
        AFTER their timed/audited passes. Returns None when the backend
        reports nothing for the decode program."""
        import jax
        from ..telemetry import memory as _mem

        def abst(x):
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype)

        B = self.max_batch
        i32 = jax.ShapeDtypeStruct((B,), np.int32)
        params = jax.tree.map(abst, self.engine.params)
        cache = jax.tree.map(abst, self.kv.cache)
        rng = abst(self._rng)
        if self._chunked:
            chunk_args = [params, cache, i32, i32,
                          jax.ShapeDtypeStruct((B,), bool), i32, i32]
            if self.fused_prefill:
                chunk_args.append(i32)    # pf_rem
                chunk_args.append(jax.ShapeDtypeStruct(
                    (self.decode_chunk, B, self.prefill_chunk),
                    np.int32))
            if self.speculative:
                chunk_args.append(
                    jax.ShapeDtypeStruct((B, self.max_seq_len), np.int32))
            chunk_args.append(rng)
            decode = _mem.compiled_memory_analysis(
                self._jit_decode_chunk, *chunk_args)
        else:
            decode = _mem.compiled_memory_analysis(
                self._jit_decode, params, cache, i32, i32, rng)
        if decode is None:
            return None
        top = self._buckets[-1]
        prefill = _mem.compiled_memory_analysis(
            self._jit_prefill, params,
            jax.ShapeDtypeStruct((B, top), np.int32), i32, rng)
        return {
            "decode_chunk": decode,
            "prefill_top_bucket": prefill,
            "prefill_bucket_len": top,
            "arena": self.kv.arena_report(),
            "live": _mem.live_array_census(top=8),
        }

    # ---------------------------------------------------------- internals
    def _next_rng(self):
        import jax
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _bucket_for(self, prompt_len: int) -> int:
        for b in self._buckets:
            if prompt_len <= b:
                return b
        return self._buckets[-1]    # unreachable: submit() length guard

    def _admit(self) -> None:
        """Admit every currently-runnable request. Dense: group by
        prefill bucket, ONE batched prefill per group, one fused arena
        insert per group. Paged: prefix-cache HITS skip prefill entirely
        (a block-table fork + the cached first token); MISSES take the
        dense prefill path, block-scattered on insert, then publish
        their prompt blocks to the prefix cache. Hit forks dispatch
        BEFORE miss inserts — dispatch order is the device write order,
        so a fork's COW source is copied before anything could recycle
        its block."""
        if self.kv_tier is not None:
            self._install_promotions()
        if self.fused_prefill:
            # chunk-budget fill policy: running lanes drain the per-step
            # token budget (a prompt chunk for prefilling lanes, one
            # decode token — k+1 speculative — for the rest); admission
            # fills what's left. The scheduler still admits one request
            # into an otherwise-idle engine so the budget can't wedge.
            admitted = self.scheduler.admit(
                token_budget=max(0, self.chunk_token_budget
                                 - self._budget_drain()),
                lane_cost=self._lane_cost)
        else:
            admitted = self.scheduler.admit()
        if not admitted:
            return
        if self.fused_prefill:
            self._fused_admit(admitted)
            if self.paged:
                self._gauge_block_pool()
            return
        if not self.paged:
            self._prefill_admit(admitted)
            return
        hits: List[Tuple[Request, Any]] = []
        misses: List[Tuple[Request, Any]] = []
        for req in admitted:
            plan = self.kv.take_plan(req.slot)
            (hits if plan.hit else misses).append((req, plan))
        for req, plan in hits:
            self._admit_prefix_hit(req, plan)
        if misses:
            self._prefill_admit([r for r, _ in misses],
                                plans={r.slot: p for r, p in misses})
        self._gauge_block_pool()

    def _admit_prefix_hit(self, req: Request, plan) -> None:
        """A cached prompt: share its full blocks, COW its tail, replay
        the stored first token. No prefill program runs — the whole
        admission is one small fork dispatch."""
        with telemetry.span("serve/prefix_fork", slot=req.slot,
                            n_shared=plan.n_shared):
            self.kv.apply_fork(plan)
        telemetry.count("serve/prefix_cache_hit")
        self.metrics.on_prefix(True)
        if plan.cow is not None:
            telemetry.instant("serve/cow_fork", slot=req.slot)
            self.metrics.on_cow()
        first = int(plan.first_token)
        self._last_token[req.slot] = first
        self.metrics.on_tokens(1)
        self.scheduler.record_first_token(req, first)
        if self._chunked:
            self._record_admit_patch(req)

    def _budget_drain(self) -> int:
        """Tokens the RUNNING lanes consume per fused scan step: one
        prompt chunk (<= C) while a lane is prefilling, one decode token
        (k+1 speculative) after."""
        C = self.prefill_chunk
        base = (1 + self.spec_k) if self.speculative else 1
        drain = 0
        for slot, req in self.scheduler.running.items():
            done = self._pf_consumed.get(slot, req.prompt_len)
            if done < req.prompt_len:
                drain += min(C, req.prompt_len - done)
            else:
                drain += base
        return drain

    def _lane_cost(self, req: Request) -> int:
        """Per-step budget cost of ADMITTING ``req`` now: its first
        prompt chunk for an inline lane; one decode token when the
        prompt takes the out-of-scan sp prefill leg instead (it joins
        the scan already in decode mode). Prefix-cache hits are priced
        as inline lanes (the hit is only known after the lease) —
        conservatively high, never starving."""
        if (self.sp_prefill_threshold is not None
                and req.prompt_len >= self.sp_prefill_threshold):
            return (1 + self.spec_k) if self.speculative else 1
        return min(self.prefill_chunk, req.prompt_len)

    def _fused_admit(self, admitted: List[Request]) -> None:
        """Fused-mode admission: no bucketed prefill program. Inline
        lanes enter the scan in prefill mode (the scan body appends
        their KV chunk by chunk); paged MISSES only install their block
        table now (the prefix commit waits for token #1); prefix HITS
        short-circuit every prompt chunk exactly like the bucketed path
        (fork + replayed first token -> straight to decode mode); and
        prompts at/above sp_prefill_threshold run the one
        sequence-parallel bucketed prefill before joining as decode
        lanes."""
        sp_reqs: List[Request] = []
        sp_plans: Dict[int, Any] = {}
        for req in admitted:
            plan = self.kv.take_plan(req.slot) if self.paged else None
            if plan is not None and plan.hit:
                self._clear_pf_slot(req.slot)
                self._admit_prefix_hit(req, plan)
                continue
            if (self.sp_prefill_threshold is not None
                    and req.prompt_len >= self.sp_prefill_threshold):
                self._clear_pf_slot(req.slot)
                sp_reqs.append(req)
                if plan is not None:
                    sp_plans[req.slot] = plan
                continue
            if plan is not None:
                # wire up the lane's block table without a KV insert —
                # the scan's chunk writes scatter through it from pos 0
                self.kv.install_table(req.slot)
                self._pf_plans[req.slot] = plan
            self._pf_consumed[req.slot] = 0
            self._pf_launched[req.slot] = 0
            self._pf_first_pending.add(req.slot)
            self._record_fused_admit_patch(req)
            telemetry.instant("serve/prefill_inline_admit",
                              slot=req.slot, prompt_len=req.prompt_len)
            if self.flight is not None:
                self.flight.record("prefill_inline_admit", uid=req.uid,
                                   slot=req.slot,
                                   prompt_len=req.prompt_len)
        if sp_reqs:
            self._prefill_admit(sp_reqs, plans=sp_plans or None)

    def _record_fused_admit_patch(self, req: Request) -> None:
        """Lane state for a freshly admitted INLINE prefill lane: pos 0,
        the full prompt outstanding (pf = prompt_len), nothing emitted.
        The carried token is a don't-care until the completing chunk
        samples token #1."""
        slot = req.slot
        rem = min(req.max_new_tokens,
                  self.kv.allocator.remaining(slot))
        eos = -1 if req.eos_token_id is None else int(req.eos_token_id)
        patch = (0, 0, rem, eos, req.prompt_len)
        if self.speculative:
            patch = patch + (self._history_row(req),)
        self._admit_patches[slot] = patch
        self._deact_slots.discard(slot)

    def _install_promotions(self) -> None:
        """Drain completed async promotions (KVTierManager's worker ran
        the NVMe read / decode off-thread) and scatter them back into
        the HBM pool — the ONLY place tier payloads touch the device, so
        the pool stays engine-thread-owned. Everything that drained
        ready in this pass installs through ONE batched scatter
        (``readmit_prefix_many`` — eager-op dispatch dominates, so k
        promotions cost one entry's dispatch). A promotion the pool
        cannot take right now goes back to the tier and retries at a
        later, less-pressured pump; nothing blocks the chunk launch."""
        ready = self.kv_tier.drain_ready()
        if not ready:
            return
        with telemetry.span("serve/tier_promote_install",
                            n=len(ready)):
            installed, rejected = self.kv.readmit_prefix_many(ready)
        for _ in installed:
            telemetry.count("serve/tier_promote")
        for key, prompt_len, first_token, leaves in rejected:
            self.kv_tier.abandon_ready(
                key, (prompt_len, first_token, leaves))

    def _gauge_block_pool(self) -> None:
        blocks = self.kv.allocator.blocks
        telemetry.gauge("serve/block_pool_used", float(blocks.n_used))
        telemetry.gauge("serve/block_pool_free", float(blocks.n_free))
        tier = self.kv_tier
        if tier is not None:
            rep = tier.report()
            telemetry.gauge("serve/tier_dram_bytes",
                            float(rep["dram_bytes"]))
            telemetry.gauge("serve/tier_nvme_bytes",
                            float(rep["nvme_bytes"]))
            telemetry.gauge("serve/tier_dram_entries",
                            float(rep["dram_entries"]))
            telemetry.gauge("serve/tier_nvme_entries",
                            float(rep["nvme_entries"]))
            telemetry.gauge("serve/tier_demotions",
                            float(rep["demotions_dram"]
                                  + rep["demotions_nvme"]))
            telemetry.gauge("serve/tier_promotions",
                            float(rep["promotions_dram"]
                                  + rep["promotions_nvme"]))
            telemetry.gauge("serve/tier_promote_wait_p50_s",
                            float(rep["promote_wait_p50_s"]))

    def _prefill_admit(self, admitted: List[Request],
                       plans: Optional[Dict[int, Any]] = None) -> None:
        """Bucketed batched prefill + fused cache insert for ``admitted``
        (the dense path verbatim; paged misses ride it too, with the
        block-scatter insert and a prefix-cache commit per request)."""
        import jax.numpy as jnp
        prof = self.profiler
        # decode slots live beyond this admission batch: every prefill
        # below pushes their next chunk launch out — the ROADMAP item-4
        # stall the profiler accounts as prefill_stall_s
        n_decoding = len(self.scheduler.running) - len(admitted)
        groups: Dict[Tuple[int, bool], List[Request]] = {}
        for req in admitted:
            use_sp = (self._jit_prefill_sp is not None
                      and self.sp_prefill_threshold is not None
                      and req.prompt_len >= self.sp_prefill_threshold)
            groups.setdefault((self._bucket_for(req.prompt_len), use_sp),
                              []).append(req)
        for (bucket, use_sp), reqs in sorted(groups.items()):
            n = len(reqs)
            prefill_fn = (self._jit_prefill_sp if use_sp
                          else self._jit_prefill)
            ids = np.zeros((n, bucket), np.int32)
            lens = np.empty(n, np.int32)
            for i, r in enumerate(reqs):
                ids[i, :r.prompt_len] = r.prompt
                lens[i] = r.prompt_len
            shape_key = (n, bucket) if not use_sp else (n, bucket, "sp")
            if shape_key not in self._prefill_shapes:
                # first sighting of this (batch, bucket) shape = the call
                # below compiles a fresh prefill program — mark it on the
                # timeline so a long prefill span is explainable
                telemetry.instant("serve/prefill_compile", n=n,
                                  bucket=bucket, sp=use_sp)
            self._prefill_shapes.add(shape_key)
            # np.asarray(toks) below is the host sync, so the span covers
            # dispatch + device prefill + arena insert honestly
            pt0 = prof.clock() if prof is not None else 0.0
            with telemetry.span("serve/prefill", n=n, bucket=bucket,
                                sp=use_sp):
                toks, cache = prefill_fn(
                    self._prefill_params, jnp.asarray(ids),
                    jnp.asarray(lens), self._next_rng())
                if self._handoff_sharding is not None:
                    # disaggregation: the finished prompt KV leaves the
                    # prefill slice here — a device-to-device transfer of
                    # the batch's cache rows onto the decode slice, where
                    # the insert scatters them through each request's
                    # table row / slot lane
                    import jax
                    nbytes = sum(
                        int(getattr(leaf, "nbytes", 0))
                        for leaf in jax.tree.leaves(cache))
                    # the handoff span carries the requests' journey ids
                    # so the transfer shows up under each trace in the
                    # merged fleet export
                    with telemetry.span(
                            "serve/disagg_handoff", n=n, bucket=bucket,
                            uids=str([r.uid for r in reqs]),
                            trace_ids=str([r.trace_id for r in reqs])):
                        cache = jax.device_put(cache,
                                               self._handoff_sharding)
                    telemetry.count("serve/disagg_handoff_bytes",
                                    float(nbytes))
                    telemetry.count("serve/disagg_handoffs", float(n))
                    if self.flight is not None:
                        self.flight.record(
                            "disagg_handoff", n=n, bytes=int(nbytes),
                            uids=[r.uid for r in reqs])
                self.kv.insert_batch(cache, [r.slot for r in reqs], lens)
                toks_host = np.asarray(toks)
            if prof is not None:
                prof.on_prefill(pt0, prof.clock(), n=n, bucket=bucket,
                                stalled=n_decoding > 0)
            telemetry.count("serve/prefill_tokens", float(lens.sum()))
            if use_sp:
                # long prompts routed over the sp mesh axis (Ulysses)
                telemetry.count("serve/sp_prefill_tokens",
                                float(lens.sum()))
            self.metrics.on_prefill(n, bucket, int(lens.sum()),
                                    len(self._prefill_shapes))
            self.metrics.on_tokens(n)
            if self.flight is not None:
                self.flight.record("prefill", n=n, bucket=bucket,
                                   uids=[r.uid for r in reqs])
            for i, r in enumerate(reqs):
                first = int(toks_host[i])
                self._last_token[r.slot] = first
                if plans is not None:
                    # publish the prompt blocks BEFORE the request can
                    # retire (retiring frees its slot refs; the cache
                    # holds its own) — may dispatch the tail COW copy
                    cow = self.kv.commit_prefix(plans[r.slot], first)
                    if self.kv.prefix_enabled:
                        telemetry.count("serve/prefix_cache_miss")
                        self.metrics.on_prefix(False)
                    if cow is not None:
                        telemetry.instant("serve/cow_fork", slot=r.slot)
                        self.metrics.on_cow()
                # may retire the request immediately (max_new_tokens == 1
                # or an instant EOS) — its slot frees before any decode
                self.scheduler.record_first_token(r, first)
                if self._chunked:
                    self._record_admit_patch(r)

    def _record_admit_patch(self, req: Request) -> None:
        slot = req.slot
        if self.fused_prefill:
            # this lane was admitted through a NON-inline path (prefix
            # hit / sp prefill): it joins the scan in pure decode mode —
            # stale inline mirrors from the slot's previous occupant
            # must not shadow it
            self._clear_pf_slot(slot)
        if req.status == "running":
            rem = min(req.max_new_tokens - len(req.tokens),
                      self.kv.allocator.remaining(slot))
            eos = -1 if req.eos_token_id is None else int(req.eos_token_id)
            patch = (int(req.tokens[-1]), req.prompt_len, rem, eos)
            if self.fused_prefill:
                patch = patch + (0,)        # pf_rem: already prefilled
            if self.speculative:
                # the drafter mines the lane's full history: patch in the
                # prompt + first token so n-gram lookup sees the prompt
                patch = patch + (self._history_row(req),)
            self._admit_patches[slot] = patch
            self._deact_slots.discard(slot)
        else:
            # instantly retired: the slot must stay dead on device
            self._admit_patches.pop(slot, None)
            self._deact_slots.add(slot)

    # ------------------------------------------------- per-token (K == 1)
    def _decode_once(self) -> None:
        import jax.numpy as jnp
        running = self.scheduler.running
        if not running:
            return
        slots = sorted(running)
        tokens = np.zeros(self.max_batch, np.int32)
        # paged: idle lanes pin the max_seq sentinel so their speculative
        # writes DROP — a stale block-table row may point at a block
        # already re-leased to another slot, so a dense-style position-0
        # write would corrupt a live request (the dense arena tolerates
        # it: each slot owns its row, and fill masks the stale entry)
        positions = np.full(self.max_batch, self.max_seq_len, np.int32) \
            if self.paged else np.zeros(self.max_batch, np.int32)
        for s in slots:
            tokens[s] = self._last_token[s]
            positions[s] = self.kv.fill[s]
        # np.asarray(tok) is the per-token host sync — the span covers
        # dispatch + device step (the K=1 reference path's whole cost)
        with telemetry.span("serve/decode_step", n=len(slots)):
            tok, new_cache = self._jit_decode(
                self._decode_params, self.kv.cache, jnp.asarray(tokens),
                jnp.asarray(positions), self._next_rng())
            self.kv.update(new_cache)
            self.kv.allocator.advance(slots)
            tok_host = np.asarray(tok)
        for s in slots:
            self._last_token[s] = int(tok_host[s])
        finished = self.scheduler.step_tokens(
            {s: int(tok_host[s]) for s in slots})
        self.metrics.on_tokens(len(slots))
        self.metrics.on_decode_step()
        self.metrics.on_finished(finished)
        self.metrics.maybe_emit(self.scheduler.queue_depth,
                                self.kv.occupancy)

    # --------------------------------------------- fused chunks (K > 1)
    def _host_state(self) -> Tuple:
        """Full chunk-input state vectors rebuilt from scheduler/allocator
        mirrors (authoritative — any pending patches are subsumed)."""
        B = self.max_batch
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        active = np.zeros(B, bool)
        remaining = np.zeros(B, np.int32)
        eos = np.full(B, -1, np.int32)
        hist = (np.zeros((B, self.max_seq_len), np.int32)
                if self.speculative else None)
        pf = np.zeros(B, np.int32) if self.fused_prefill else None
        for slot, req in self.scheduler.running.items():
            done = (self._pf_consumed.get(slot, req.prompt_len)
                    if self.fused_prefill else req.prompt_len)
            if pf is not None and done < req.prompt_len:
                # mid-prompt lane: resumes in prefill mode; tokens come
                # from the prompt buffer, not the carried last token
                tokens[slot] = 0
                positions[slot] = done
                pf[slot] = req.prompt_len - done
                remaining[slot] = min(
                    req.max_new_tokens - len(req.tokens),
                    self.kv.allocator.remaining(slot))
            else:
                tokens[slot] = self._last_token[slot]
                positions[slot] = self.kv.fill[slot]
                remaining[slot] = min(
                    req.max_new_tokens - len(req.tokens),
                    self.kv.allocator.remaining(slot))
            active[slot] = True
            if req.eos_token_id is not None:
                eos[slot] = int(req.eos_token_id)
            if hist is not None:
                hist[slot] = self._history_row(req)
        self._deact_slots.clear()
        self._admit_patches.clear()
        if self.fused_prefill:
            # a host rebuild collapses the launch horizon back onto the
            # consumed cursor (any launched-but-unconsumed chunk is gone
            # with the discarded in-flight chunk)
            self._pf_launched = dict(self._pf_consumed)
        out = (tokens, positions, active, remaining, eos)
        if pf is not None:
            out = out + (pf,)
        if hist is not None:
            out = out + (hist,)
        return out

    def _history_row(self, req: Request) -> np.ndarray:
        """One lane's token history (prompt + emitted) padded to
        [max_seq_len] — the drafter's lookup corpus. Invariant:
        ``row[positions[slot]] == last_token[slot]``."""
        row = np.zeros(self.max_seq_len, np.int32)
        seq = list(np.asarray(req.prompt).tolist()) + \
            [int(t) for t in req.tokens]
        n = min(len(seq), self.max_seq_len)
        row[:n] = seq[:n]
        return row

    def _device_state(self, chunk: _InflightChunk) -> Tuple:
        """Chunk-input state propagated on DEVICE from the previous
        chunk's carry (no host sync), with the host's corrections patched
        in: lanes the scheduler finished for its own reasons (deadline)
        go inactive; freshly admitted requests get their full lane
        state."""
        tok, pos, act, rem, eos = chunk.state[:5]
        i = 5
        pf = None
        if self.fused_prefill:
            pf = chunk.state[i]
            i += 1
        hist = chunk.state[i] if self.speculative else None
        if self._deact_slots:
            telemetry.instant("serve/deact_patch",
                              n=len(self._deact_slots))
            if self.flight is not None:
                self.flight.record("deact_patch",
                                   slots=sorted(self._deact_slots))
            idx = np.array(sorted(self._deact_slots), np.int32)
            act = act.at[idx].set(False)
        if self._admit_patches:
            telemetry.instant("serve/admit_patch",
                              n=len(self._admit_patches))
            if self.flight is not None:
                self.flight.record("admit_patch",
                                   slots=sorted(self._admit_patches))
            slots = np.array(sorted(self._admit_patches), np.int32)
            vals = [self._admit_patches[int(s)] for s in slots]
            tok = tok.at[slots].set(
                np.array([v[0] for v in vals], np.int32))
            pos = pos.at[slots].set(
                np.array([v[1] for v in vals], np.int32))
            rem = rem.at[slots].set(
                np.array([v[2] for v in vals], np.int32))
            eos = eos.at[slots].set(
                np.array([v[3] for v in vals], np.int32))
            act = act.at[slots].set(True)
            vi = 4
            if pf is not None:
                pf = pf.at[slots].set(
                    np.array([v[vi] for v in vals], np.int32))
                vi += 1
            if hist is not None:
                hist = hist.at[slots].set(
                    np.stack([v[vi] for v in vals]))
        self._deact_slots.clear()
        self._admit_patches.clear()
        out = (tok, pos, act, rem, eos)
        if pf is not None:
            out = out + (pf,)
        if hist is not None:
            out = out + (hist,)
        return out

    def _launch_chunk(self, state: Tuple) -> _InflightChunk:
        """Enqueue one K-step decode chunk (returns immediately — JAX
        async dispatch; nothing here blocks on device results)."""
        import jax.numpy as jnp
        prof = self.profiler
        t0 = prof.clock() if prof is not None else 0.0
        # dispatch-only span BY DESIGN (no sync=): the chunk is meant to
        # run asynchronously; the honest device wait is measured at
        # consume time as serve/chunk_host_wait
        with telemetry.span("serve/chunk_launch", k=self.decode_chunk):
            if self.fused_prefill:
                state = tuple(jnp.asarray(a) for a in state)
                tokens, positions, active, remaining, eos, pf = (
                    state[0], state[1], state[2], state[3], state[4],
                    state[5])
                pbuf = jnp.asarray(self._build_prompt_buf())
                if self.speculative:
                    hist = state[6]
                    (toks, valid, new_cache, tok_f, pos_f, act_f, rem_f,
                     pf_f, hist_f) = self._jit_decode_chunk(
                        self._decode_params, self.kv.cache, tokens,
                        positions, active, eos, remaining, pf, pbuf,
                        hist, self._next_rng())
                    carry = (tok_f, pos_f, act_f, rem_f, eos, pf_f,
                             hist_f)
                else:
                    (toks, valid, new_cache, tok_f, pos_f, act_f, rem_f,
                     pf_f) = self._jit_decode_chunk(
                        self._decode_params, self.kv.cache, tokens,
                        positions, active, eos, remaining, pf, pbuf,
                        self._next_rng())
                    carry = (tok_f, pos_f, act_f, rem_f, eos, pf_f)
            elif self.speculative:
                (tokens, positions, active, remaining, eos, hist) = (
                    jnp.asarray(a) for a in state)
                (toks, valid, new_cache, tok_f, pos_f, act_f, rem_f,
                 hist_f) = self._jit_decode_chunk(
                    self._decode_params, self.kv.cache, tokens, positions,
                    active, eos, remaining, hist, self._next_rng())
                carry = (tok_f, pos_f, act_f, rem_f, eos, hist_f)
            else:
                tokens, positions, active, remaining, eos = (
                    jnp.asarray(a) for a in state)
                toks, valid, new_cache, tok_f, pos_f, act_f, rem_f = \
                    self._jit_decode_chunk(
                        self._decode_params, self.kv.cache, tokens,
                        positions, active, eos, remaining,
                        self._next_rng())
                carry = (tok_f, pos_f, act_f, rem_f, eos)
            self.kv.update(new_cache)
        inflight = _InflightChunk(
            slot_uids={s: r.uid for s, r in self.scheduler.running.items()},
            tokens=toks, valid=valid, state=carry,
            wall_t0=time.perf_counter())
        if prof is not None:
            t1 = prof.clock()
            inflight.launch_t = t1
            prof.on_launch(t0, t1, n_slots=len(inflight.slot_uids))
        if self.flight is not None:
            self.flight.record("chunk_launch", k=self.decode_chunk,
                               slot_uids=dict(inflight.slot_uids))
        return inflight

    def _consume_chunk(self, chunk: _InflightChunk) -> List[Request]:
        """Block on the chunk's token buffer (the ONE host sync per K
        steps) and feed it through the scheduler."""
        prof = self.profiler
        hw0 = prof.clock() if prof is not None else 0.0
        with telemetry.span("serve/chunk_host_wait"):
            toks = np.asarray(chunk.tokens)
            valid = np.asarray(chunk.valid)
        rt0 = prof.clock() if prof is not None else 0.0
        if self._overlap_active and chunk.wall_t0:
            # cumulative wall seconds of decode chunks served with the
            # RS/AG collective/MLP overlap decomposition active
            self._overlap_seconds += time.perf_counter() - chunk.wall_t0
            telemetry.gauge("serve/collective_overlap_s",
                            self._overlap_seconds)
        inline_tokens = 0
        n_first = 0
        pf_steps = None
        with telemetry.span("serve/chunk_retire"):
            if self.fused_prefill:
                # deterministic host replay of the chunk's prefill-mode
                # evolution: advances the consumed cursors and yields the
                # per-lane pf-step mask for accounting
                consumed, pf_steps = self._sim_chunk_prefill(chunk)
                for slot, done in consumed.items():
                    prev = self._pf_consumed.get(slot, done)
                    inline_tokens += max(done - prev, 0)
                    self._pf_consumed[slot] = done
            fin_before = len(self.scheduler.finished)
            per_slot: Dict[int, List[int]] = {}
            for slot, uid in chunk.slot_uids.items():
                req = self.scheduler.running.get(slot)
                if req is None or req.uid != uid:
                    continue        # slot retired/re-leased since launch
                seq = [int(t) for t, v in
                       zip(toks[slot], valid[slot]) if v]
                if (self.fused_prefill and seq
                        and slot in self._pf_first_pending):
                    # the lane completed its prompt inside this chunk:
                    # token #1 routes through record_first_token (TTFT
                    # stamp, NO allocator advance — its KV row is written
                    # by the next decode step), and a deferred paged
                    # admit plan publishes the prompt blocks now
                    self._pf_first_pending.discard(slot)
                    first = seq.pop(0)
                    n_first += 1
                    plan = self._pf_plans.pop(slot, None)
                    if plan is not None:
                        cow = self.kv.commit_prefix(plan, first)
                        if self.kv.prefix_enabled:
                            telemetry.count("serve/prefix_cache_miss")
                            self.metrics.on_prefix(False)
                        if cow is not None:
                            telemetry.instant("serve/cow_fork", slot=slot)
                            self.metrics.on_cow()
                    self._last_token[slot] = first
                    self.scheduler.record_first_token(req, first)
                    if req.status != "running":
                        seq = []    # retired on token #1: drop the rest
                if seq:
                    per_slot[slot] = seq
                    self._last_token[slot] = seq[-1]
            self.scheduler.step_tokens_chunk(per_slot)
            finished = self.scheduler.finished[fin_before:]
        rt1 = prof.clock() if prof is not None else 0.0
        n_tokens = sum(len(v) for v in per_slot.values())
        proposed = accepted = 0
        if self.flight is not None:
            self.flight.record("chunk_retire", n_tokens=n_tokens,
                               finished=[r.uid for r in finished],
                               queue_depth=self.scheduler.queue_depth,
                               occupancy=float(self.kv.occupancy))
        telemetry.count("serve/decode_tokens", float(n_tokens))
        decode_iters = n_tokens      # 1 token per live decode step
        if inline_tokens:
            telemetry.count("serve/prefill_inline_tokens",
                            float(inline_tokens))
            self.inline_prefill_tokens += inline_tokens
        if n_first:
            self.metrics.on_tokens(n_first)
        if self.speculative:
            # acceptance accounting from the validity mask itself: a
            # step is live iff its base position (j == 0, the correction
            # /bonus slot always valid on live lanes) is valid; accepted
            # drafts = valid tokens beyond that guaranteed one. In fused
            # mode a prefill-mode step also has column 0 valid on its
            # completing iteration (token #1) but verified no drafts —
            # the host-replayed pf mask excludes those steps
            kp1 = self.spec_k + 1
            W = max(self.prefill_chunk, kp1) if self.fused_prefill \
                else kp1
            v3 = valid.reshape(self.max_batch, -1, W)
            live_steps = v3[:, :, 0]
            if pf_steps is not None:
                live_steps = live_steps & ~pf_steps
            decode_iters = int(live_steps.sum())
            proposed = decode_iters * self.spec_k
            accepted = int(np.maximum(
                np.where(live_steps, v3.sum(axis=2), 0) - live_steps,
                0).sum())
            if proposed:
                telemetry.count("serve/spec_proposed", float(proposed))
                telemetry.count("serve/spec_accepted", float(accepted))
            self.metrics.on_spec(proposed, accepted)
        telemetry.gauge("serve/queue_depth",
                        float(self.scheduler.queue_depth))
        telemetry.gauge("serve/occupancy", float(self.kv.occupancy))
        if self.paged:
            self._gauge_block_pool()
            telemetry.gauge("serve/arena_headroom_bytes",
                            float(self.kv.allocator.blocks.n_free
                                  * self._bytes_per_block))
        else:
            telemetry.gauge("serve/arena_headroom_bytes",
                            float(self.kv.allocator.n_free
                                  * self._arena_bytes_per_slot))
        if prof is not None:
            if self.fused_prefill:
                pf_total = int(pf_steps.sum()) if pf_steps is not None \
                    else 0
                prof.on_chunk(
                    launch_t=chunk.launch_t, hw0=hw0,
                    hw1=rt0, rt0=rt0, rt1=rt1,
                    n_tokens=n_tokens,
                    occupancy=float(self.kv.occupancy),
                    proposed=proposed, accepted=accepted,
                    inline_pf_tokens=inline_tokens,
                    # every fused scan iteration is the same C-wide
                    # compute: split the device span by step count
                    inline_pf_frac=pf_total / max(
                        pf_total + decode_iters, 1))
            else:
                prof.on_chunk(launch_t=chunk.launch_t, hw0=hw0,
                              hw1=rt0, rt0=rt0, rt1=rt1,
                              n_tokens=n_tokens,
                              occupancy=float(self.kv.occupancy),
                              proposed=proposed, accepted=accepted)
        self.metrics.on_tokens(n_tokens)
        self.metrics.on_decode_step()
        self.metrics.on_finished(finished)
        for req in finished:
            if req.slot is not None:
                self._deact_slots.add(req.slot)
                if self.fused_prefill:
                    self._clear_pf_slot(req.slot)
        self.metrics.maybe_emit(self.scheduler.queue_depth,
                                self.kv.occupancy)
        return finished

    def _build_prompt_buf(self) -> np.ndarray:
        """Per-scan-step prompt chunks [K, B, C] for lanes still in
        prefill mode, advancing the LAUNCH cursor (it runs one chunk
        horizon ahead of the consumed cursor under double-buffering).
        Prefill-mode evolution on device is deterministic — a lane mid-
        prompt cannot EOS or exhaust its budget — so this host mirror
        stays exact without a device sync."""
        K, B, C = self.decode_chunk, self.max_batch, self.prefill_chunk
        buf = np.zeros((K, B, C), np.int32)
        for slot, req in self.scheduler.running.items():
            done = self._pf_launched.get(slot)
            if done is None:
                continue
            prompt = np.asarray(req.prompt, np.int32)
            L = req.prompt_len
            for k in range(K):
                if done >= L:
                    break
                n = min(C, L - done)
                buf[k, slot, :n] = prompt[done:done + n]
                done += n
            self._pf_launched[slot] = done
        return buf

    def _sim_chunk_prefill(
            self, chunk: _InflightChunk
    ) -> Tuple[Dict[int, int], np.ndarray]:
        """Deterministic host replay of the consumed chunk's prefill-
        mode evolution (mirrors the device mask exactly: each step a
        mid-prompt lane consumes ``min(pf, C)`` tokens). Returns the
        advanced consumed cursors and the [B, K] mask of steps each
        lane spent in prefill mode (its completing step — the one that
        emits token #1 — included)."""
        K, C = self.decode_chunk, self.prefill_chunk
        pf_steps = np.zeros((self.max_batch, K), bool)
        consumed: Dict[int, int] = {}
        for slot, uid in chunk.slot_uids.items():
            req = self.scheduler.running.get(slot)
            if req is None or req.uid != uid:
                continue
            done = self._pf_consumed.get(slot)
            if done is None or done >= req.prompt_len:
                continue
            L = req.prompt_len
            for k in range(K):
                if done >= L:
                    break
                pf_steps[slot, k] = True
                done += min(C, L - done)
            consumed[slot] = done
        return consumed, pf_steps

    def _may_outlive_chunk(self) -> bool:
        """Could any lane still be live AFTER the in-flight chunk? (Host
        mirrors are pre-chunk here, so a lane survives it only if its
        remaining budget exceeds K.) Gates the speculative next-chunk
        launch so the drain tail doesn't pay a fully-dead chunk."""
        K = self.decode_chunk
        for slot, req in self.scheduler.running.items():
            if (self.fused_prefill
                    and self._pf_consumed.get(slot, req.prompt_len)
                    < req.prompt_len):
                return True      # still mid-prompt: more chunks coming
            rem = min(req.max_new_tokens - len(req.tokens),
                      self.kv.allocator.remaining(slot))
            if rem > K:
                return True
        return False

    def _serve_pipelined(self) -> None:
        """The async host loop: always keep one chunk in flight, and
        enqueue its successor (from device-carried state) BEFORE blocking
        on its token buffer — host-side scheduling/bookkeeping overlaps
        device compute. Host-only events (deadline expiry, cancellation,
        admissions) take effect one chunk late; device-detected stops
        (EOS, budget) take effect immediately via the carried active
        mask. One ``pump()`` call per iteration — the same loop an
        external driver (the serving frontend) runs incrementally."""
        while self.scheduler.has_work() or self._pending is not None:
            self.pump()

    def close(self) -> None:
        """Release host-side serving resources: the KV tier's promotion
        worker and its NVMe spill files. Idempotent; engines without a
        tier have nothing to release."""
        if self.kv_tier is not None:
            self.kv_tier.close()
