"""Slotted KV-cache management for continuous-batching serving.

Reference analogue: the inference kernel's per-request KV arena
(``csrc/transformer/inference/includes/context.h`` allocates one workspace
sized ``[max_out_tokens, ...]`` per layer and hands each request a region).
Here the arena is the model's own flax ``cache`` collection, widened to a
fixed ``[max_batch]`` slot axis with a PER-SLOT fill index — the vLLM/
PagedAttention idea specialized to TPU constraints: rather than paging
variable-sized blocks (dynamic shapes XLA would recompile on), every
request leases one fixed ``[max_seq, ...]`` slot row, and slot reuse is a
single fused ``dynamic_update_slice`` per cache leaf.

Two layers, deliberately separable:
  * :class:`SlotAllocator` — pure host-side accounting (free list, per-slot
    fill lengths, occupancy). No JAX. Unit-testable at CPU speed.
  * :class:`SlotKVCacheManager` — owns the device arena pytree and the
    jitted slot-insert program; composes a SlotAllocator for the
    bookkeeping.
"""

from __future__ import annotations

import heapq
from functools import partial
from typing import Any, List, Optional

import numpy as np


class SlotAllocator:
    """Host-side slot accounting: a fixed pool of ``max_batch`` cache rows,
    each leased to at most one in-flight request, with per-slot fill
    lengths (number of valid KV positions). Lowest-index-first allocation
    keeps runs deterministic."""

    def __init__(self, max_batch: int, max_seq_len: int):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self._free: List[int] = list(range(max_batch))
        heapq.heapify(self._free)
        self.fill = np.zeros(max_batch, np.int32)
        self.active = np.zeros(max_batch, bool)

    # ------------------------------------------------------------- leases
    def alloc(self, fill_len: int = 0) -> Optional[int]:
        """Lease the lowest free slot at ``fill_len`` valid positions;
        None when every slot is busy (caller applies backpressure)."""
        if not self._free:
            return None
        if fill_len > self.max_seq_len:
            raise ValueError(
                f"fill_len {fill_len} exceeds max_seq_len {self.max_seq_len}")
        slot = heapq.heappop(self._free)
        self.active[slot] = True
        self.fill[slot] = fill_len
        return slot

    def free(self, slot: int) -> None:
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self.active[slot] = False
        self.fill[slot] = 0
        heapq.heappush(self._free, slot)

    def advance(self, slots) -> None:
        """One decode step wrote one token into each of ``slots``."""
        self.fill[np.asarray(slots, np.int64)] += 1

    # ------------------------------------------------------------ queries
    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.max_batch

    def remaining(self, slot: int) -> int:
        """Cache positions still writable in this slot's row."""
        return self.max_seq_len - int(self.fill[slot])


class SlotKVCacheManager:
    """The device arena: the model's flax ``cache`` pytree widened to
    ``[..., max_batch, max_seq, ...]`` with per-slot ``cache_index``
    vectors, plus the jitted insert that moves one prefilled request's KV
    into its leased slot row.

    ``slot_axis``: position of the batch/slot axis in the cached k/v
    leaves — 1 when the model scans its layers (leaves are stacked
    ``[L, B, S, ...]``), 0 otherwise.
    """

    def __init__(self, model, params, max_batch: int, *,
                 slot_axis: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        cfg = getattr(model, "cfg", None)
        self.max_seq_len = int(getattr(cfg, "max_seq_len"))
        # fp itemsize the arena WOULD use without int8 KV — the baseline
        # for arena_report's kv_bytes_saved accounting
        self._fp_itemsize = int(jnp.dtype(
            getattr(cfg, "dtype", jnp.float32)).itemsize)
        self.allocator = SlotAllocator(max_batch, self.max_seq_len)
        if slot_axis is None:
            slot_axis = 1 if getattr(cfg, "scan_layers", False) else 0
        self._slot_axis = slot_axis

        # Arena construction via eval_shape: no compute, no compile — just
        # the cache pytree the decode path would allocate for a [B, 1]
        # step, with every leaf zeroed and the scalar-per-layer
        # ``cache_index`` widened to a per-slot [..., B] vector (the shape
        # models/gpt.py's _decode_attention dispatches per-slot mode on).
        ids = jnp.zeros((max_batch, 1), jnp.int32)
        pos = jnp.zeros((max_batch, 1), jnp.int32)
        shapes = jax.eval_shape(
            partial(model.apply, mutable=["cache"]),
            {"params": params}, ids, positions=pos)
        cache_shapes = shapes[1]["cache"]

        def build(path, leaf):
            if "cache_index" in jax.tree_util.keystr(path):
                return jnp.zeros(leaf.shape + (max_batch,), jnp.int32)
            return jnp.zeros(leaf.shape, leaf.dtype)

        self.cache = jax.tree_util.tree_map_with_path(build, cache_shapes)

        ax = self._slot_axis

        @partial(jax.jit, donate_argnums=(0,))
        def _insert(arena, one, slot, fill):
            def leaf(a, o):
                if a.ndim == o.ndim:        # cached_key / cached_value rows
                    start = tuple(slot if i == ax else 0
                                  for i in range(a.ndim))
                    return jax.lax.dynamic_update_slice(
                        a, o.astype(a.dtype), start)
                # per-slot fill vector: the TRUE prompt length, not the
                # prefill program's padded index
                return a.at[..., slot].set(fill)
            return jax.tree.map(leaf, arena, one)

        self._insert = _insert

        @partial(jax.jit, donate_argnums=(0,))
        def _insert_batch(arena, batched, slots, fills):
            """Move a batch-n bucketed prefill cache into n leased slot
            rows. The prefill leaves are [.., n, P_bucket, ..] with
            P_bucket <= max_seq — only the bucket's prefix of each row is
            overwritten; stale tail positions from a previous occupant
            stay masked (fill < their position) until the new request's
            own decode writes them, so they are never attended."""
            def leaf(a, o):
                if a.ndim == o.ndim:        # cached_key / cached_value rows
                    for i in range(o.shape[ax]):    # n <= max_batch: unroll
                        row = jax.lax.dynamic_slice_in_dim(o, i, 1, axis=ax)
                        start = tuple(slots[i] if j == ax else 0
                                      for j in range(a.ndim))
                        a = jax.lax.dynamic_update_slice(
                            a, row.astype(a.dtype), start)
                    return a
                # per-slot fill vector: scatter the TRUE prompt lengths
                return a.at[..., slots].set(fills)
            return jax.tree.map(leaf, arena, batched)

        self._insert_batch = _insert_batch

    # ----------------------------------------------------------- mutation
    def insert(self, prefill_cache: Any, slot: int, fill_len: int) -> None:
        """Move a batch-1 prefilled cache into slot ``slot`` and pin its
        fill at ``fill_len`` (the unpadded prompt length). Donates and
        replaces the arena — one fused copy per cache leaf."""
        self.cache = self._insert(self.cache, prefill_cache,
                                  np.int32(slot), np.int32(fill_len))

    def insert_batch(self, prefill_cache: Any, slots, fills) -> None:
        """Move a batch-n bucketed prefill cache (leaves [.., n, P, ..])
        into the n slot rows ``slots``, pinning each slot's fill at its
        TRUE prompt length. Donates and replaces the arena. Compiles one
        program per (n, P_bucket) pair — the same lazy shape family as the
        bucketed prefill itself."""
        import jax.numpy as jnp
        self.cache = self._insert_batch(
            self.cache, prefill_cache,
            jnp.asarray(np.asarray(slots, np.int32)),
            jnp.asarray(np.asarray(fills, np.int32)))

    def update(self, new_cache: Any) -> None:
        """Adopt the cache returned by a (donating) decode step."""
        self.cache = new_cache

    # ---------------------------------------------------------- accounting
    def arena_report(self) -> dict:
        """HBM accounting of the arena pytree: total/kv/index bytes plus
        the derived per-slot and per-token costs and the current
        headroom (bytes of KV the free slots could still hold). This is
        the ground truth the admission cost model and the bench ``hbm``
        block read — computed from the live leaves, so dtype changes
        (e.g. a future int8 KV) are reflected automatically."""
        import jax
        import numpy as _np
        kv_bytes = 0
        index_bytes = 0
        int8_payload = 0            # quantized cached_key/value bytes
        scale_bytes = 0             # per-token f32 dequant multipliers
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is None:
                continue
            key = jax.tree_util.keystr(path)
            if "cache_index" in key:
                index_bytes += int(nbytes)
            else:
                kv_bytes += int(nbytes)
                if "scale" in key:
                    scale_bytes += int(nbytes)
                elif leaf.dtype == _np.int8:
                    int8_payload += int(nbytes)
        # what the SAME payload would cost in the model's fp dtype (scale
        # leaves don't exist in fp mode): saved = fp-equivalent - actual
        kv_bytes_fp = (kv_bytes - int8_payload - scale_bytes
                       + int8_payload * self._fp_itemsize)
        alloc = self.allocator
        per_slot = kv_bytes // alloc.max_batch if alloc.max_batch else 0
        per_token = per_slot // self.max_seq_len if self.max_seq_len else 0
        return {
            "arena_bytes": kv_bytes + index_bytes,
            "kv_bytes": kv_bytes,
            "index_bytes": index_bytes,
            "int8_payload_bytes": int8_payload,
            "scale_bytes": scale_bytes,
            "kv_bytes_fp_equiv": kv_bytes_fp,
            "kv_bytes_saved": kv_bytes_fp - kv_bytes,
            "max_batch": alloc.max_batch,
            "max_seq_len": self.max_seq_len,
            "bytes_per_slot": per_slot,
            "bytes_per_token": per_token,
            "n_active": alloc.n_active,
            "n_free": alloc.n_free,
            "active_bytes": alloc.n_active * per_slot,
            "headroom_bytes": alloc.n_free * per_slot,
        }

    # ---------------------------------------------- allocator passthrough
    def alloc(self, fill_len: int = 0) -> Optional[int]:
        return self.allocator.alloc(fill_len)

    def free(self, slot: int) -> None:
        self.allocator.free(slot)

    @property
    def fill(self) -> np.ndarray:
        return self.allocator.fill

    @property
    def occupancy(self) -> float:
        return self.allocator.occupancy
