"""ServingFrontend: the thread-safe control plane between many callers
and one ``ServingEngine``.

PRs 1-2 built a fast continuous-batching core, but it is a synchronous,
single-caller loop: ``run()`` owns the engine until it drains. A serving
tier needs the opposite shape — many concurrent callers, each getting an
incremental token stream, with admission shaped against priorities and
SLOs instead of arrival order. This module adds that shape without
touching the device programs:

* ``ServingFrontend.submit(prompt, *, priority, slo_ttft_s, deadline_s)``
  returns a :class:`StreamHandle` immediately from any thread;
* one background **engine-driver thread** owns every engine/scheduler
  touch (the core stays single-threaded by construction) and runs the
  same double-buffered chunk loop ``run()`` uses, via
  ``ServingEngine.pump()``;
* tokens stream to handles as each decode chunk retires (blocking
  iterator or non-blocking ``poll``), at chunk granularity — one
  delivery per ``decode_chunk`` tokens;
* ``cancel()`` frees the slot within one chunk through the engine's
  host-event patch path; ``close()`` drains in-flight work; a driver
  crash hands every outstanding handle to the fleet ``on_crash`` hook
  for replay on a survivor (``adopt`` re-prefills prompt + emitted
  tokens and dedups on emitted-token count), or resolves it ``error``
  when no hook/survivor exists — callers never hang;
* admission decisions (priority ordering, deadline-feasibility shedding,
  per-tenant rate limits) live in :mod:`.admission`; per-request spans
  and latency histograms in :mod:`.tracing`.

Terminal handle statuses: ``done | cancelled | rejected | error |
expired`` (``expired`` = admitted but its deadline passed mid-stream —
distinguished from ``rejected``, which never consumed device time).

Granularity contract: the driver observes the engine only at chunk
boundaries, so cancellation and deadline expiry take effect within one
decode chunk (up to ``decode_chunk - 1`` tokens of device work are
wasted, never delivered), and streamed tokens arrive in bursts of up to
``decode_chunk``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ...analysis import locks
from ...telemetry import core as telemetry
from ...telemetry.flight_recorder import FlightRecorder
from ...telemetry.journey import new_trace_id
from ...utils.logging import logger
from ..engine import MigrationError
from ..scheduler import Request
from .admission import (AdmissionConfig, AdmissionController,
                        ChunkThroughputEstimator, PRIORITY_NORMAL,
                        REJECT_FRONTEND_CLOSED, Ticket)
from .tracing import TraceLog

#: statuses after which a handle will never change again
TERMINAL_STATUSES = ("done", "cancelled", "rejected", "error", "expired")

#: versioned wire schemas (the transport serializes these verbatim)
LOAD_SCHEMA = "dstpu-load-v1"
SNAPSHOT_SCHEMA = "dstpu-snapshot-v1"


class StreamHandle:
    """One caller's view of one request: a thread-safe incremental token
    stream plus the terminal status. Produced by
    :meth:`ServingFrontend.submit`; all methods are safe from any
    thread."""

    def __init__(self, request: Request, frontend: "ServingFrontend", *,
                 tenant: str, priority: int,
                 slo_ttft_s: Optional[float], submit_t: float,
                 trace_id: Optional[str] = None):
        self._request = request
        self._frontend = frontend
        # the ORIGINAL prompt and token budget, immutable for the
        # handle's lifetime: crash replay rewrites the Request's prompt
        # to prompt+emitted and shrinks its budget, so caller-facing
        # views (output_ids, request_snapshot) must read these instead
        self._prompt = np.asarray(request.prompt, np.int32)
        self._max_new_tokens = int(request.max_new_tokens)
        self.tenant = tenant
        self.priority = priority
        self.slo_ttft_s = slo_ttft_s
        self.submit_t = submit_t
        self.trace_id = trace_id       # distributed journey id (immutable)
        self._cond = locks.make_condition("frontend.stream_handle")
        self._tokens: List[int] = []
        self._cursor = 0               # poll()/iterator read position
        self._status: Optional[str] = None
        self._reject_reason: Optional[str] = None
        self._error: Optional[str] = None
        # driver-thread-only bookkeeping (never touched by callers)
        self._ticket: Optional[Ticket] = None
        self._pushed = 0               # tokens handed to _push so far
        self._prefill_marked = False

    # ----------------------------------------------------- driver side
    def _push(self, tokens: Sequence[int]) -> None:
        with self._cond:
            if self._status is not None:
                return                 # terminal: late tokens are dropped
            self._tokens.extend(int(t) for t in tokens)
            self._cond.notify_all()

    def _resolve(self, status: str, *, reject_reason: Optional[str] = None,
                 error: Optional[str] = None) -> None:
        with self._cond:
            if self._status is not None:
                return                 # first terminal status wins
            self._status = status
            self._reject_reason = reject_reason
            self._error = error
            self._cond.notify_all()

    # ----------------------------------------------------- caller side
    @property
    def uid(self) -> int:
        return self._request.uid

    @property
    def status(self) -> str:
        """``"pending"`` until terminal, then one of
        :data:`TERMINAL_STATUSES`."""
        with self._cond:
            return self._status or "pending"

    @property
    def done(self) -> bool:
        with self._cond:
            return self._status is not None

    @property
    def reject_reason(self) -> Optional[str]:
        with self._cond:
            return self._reject_reason

    @property
    def error(self) -> Optional[str]:
        with self._cond:
            return self._error

    @property
    def tokens(self) -> List[int]:
        """All tokens streamed so far (copy; does not consume the
        ``poll``/iterator cursor)."""
        with self._cond:
            return list(self._tokens)

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + streamed tokens — the ``Request.output_ids``
        contract, so streamed results compare bit-for-bit against
        ``ServingEngine.run``."""
        with self._cond:
            toks = np.asarray(self._tokens, np.int32)
        return np.concatenate([self._prompt, toks])

    def poll(self) -> List[int]:
        """Non-blocking: tokens that arrived since the last
        ``poll``/iteration step (empty list when none)."""
        with self._cond:
            new = self._tokens[self._cursor:]
            self._cursor = len(self._tokens)
            return [int(t) for t in new]

    def __iter__(self):
        """Blocking token stream; ends when the request reaches a
        terminal status (after yielding every delivered token)."""
        while True:
            with self._cond:
                while self._cursor >= len(self._tokens) and \
                        self._status is None:
                    self._cond.wait()
                if self._cursor < len(self._tokens):
                    tok = int(self._tokens[self._cursor])
                    self._cursor += 1
                else:
                    return
            yield tok

    def result(self, timeout: Optional[float] = None) -> str:
        """Block until terminal; returns the terminal status. Raises
        ``TimeoutError`` if the deadline passes first."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._status is not None,
                                       timeout):
                raise TimeoutError(
                    f"request uid={self.uid} not terminal after "
                    f"{timeout}s (status=pending)")
            return self._status

    def cancel(self) -> None:
        """Request cancellation (idempotent, safe from any thread). The
        handle resolves to ``cancelled`` once the driver processes it —
        within one decode chunk."""
        self._frontend.cancel(self)


class ServingFrontend:
    """Thread-safe serving front end over one :class:`ServingEngine`.

    The frontend OWNS the engine's execution: after construction, no
    other code may call ``run``/``step``/``pump`` on it. A single daemon
    driver thread performs every engine and scheduler access; callers
    interact only through thread-safe ``submit``/``cancel``/``close``
    and StreamHandles.

    ``feed_depth`` bounds how many admission winners sit in the engine
    scheduler's FIFO at once (default ``max_batch``): priority decisions
    stay in the frontend's heap until the engine can actually use the
    request, keeping the priority-inversion window one batch wide.
    """

    def __init__(self, engine, *,
                 admission: Optional[AdmissionConfig] = None,
                 monitor=None,
                 feed_depth: Optional[int] = None,
                 idle_wait_s: float = 0.005,
                 emit_every_s: float = 1.0,
                 trace_keep_last: int = 256,
                 on_crash=None,
                 telemetry_label: Optional[str] = None,
                 flight_recorder: Optional[FlightRecorder] = None,
                 clock=time.monotonic):
        self._engine = engine
        self._clock = clock
        # fleet hooks: ``on_crash(frontend, salvaged_handles, exc)`` gets
        # the never-prefilled work when the driver dies (the router
        # re-homes it on survivors); ``telemetry_label`` tags every metric
        # the driver thread records with ``replica=<label>``
        self._on_crash = on_crash
        self._telemetry_label = telemetry_label
        self._controller = AdmissionController(admission, clock=clock)
        cfg = self._controller.config
        if cfg.shed_memory_infeasible and cfg.slot_tokens is None:
            # memory-aware shedding sized from the engine's own arena:
            # one slot row holds at most max_seq_len KV positions (for a
            # paged pool SMALLER than one full row per slot, the pool
            # itself is the tighter wall)
            cfg.slot_tokens = engine.max_seq_len
            pool_cap = getattr(getattr(engine, "kv", None), "allocator",
                               None)
            pool_cap = getattr(pool_cap, "pool_capacity_tokens", None)
            if pool_cap is not None:
                cfg.slot_tokens = min(cfg.slot_tokens, int(pool_cap))
        if cfg.shed_memory_infeasible and \
                getattr(engine, "kv_tier", None) is not None:
            # tiered KV: DRAM+NVMe capacity counts toward AGGREGATE
            # feasibility at a discounted rate (tier_discount) — the
            # pending queue's total KV demand may exceed the HBM pool
            # (pool_tokens) by the tier's discounted headroom. The
            # per-ticket wall stays pure-HBM (slot_tokens): an active
            # sequence's KV can never live below HBM, so a request
            # that cannot fit one slot row / the pool is infeasible
            # no matter how deep the tier is.
            rep = engine.kv.arena_report()
            bpt = max(int(rep.get("bytes_per_token", 0)), 1)
            if cfg.tier_tokens is None:
                tier = engine.kv_tier
                tier_bytes = int(tier.dram_capacity)
                if tier.nvme_capacity is not None:
                    tier_bytes += int(tier.nvme_capacity)
                cfg.tier_tokens = tier_bytes // bpt
            if cfg.pool_tokens is None:
                pool_cap = getattr(
                    getattr(engine.kv, "allocator", None),
                    "pool_capacity_tokens", None)
                cfg.pool_tokens = int(pool_cap) if pool_cap is not None \
                    else cfg.slot_tokens
        if cfg.fused_prefill_chunk is None and \
                getattr(engine, "fused_prefill", False):
            # fused chunked prefill: prompts ride the decode scan at
            # prefill_chunk tokens per step, so the admission cost model
            # counts scan steps, not bucket-weighted prompt tokens
            cfg.fused_prefill_chunk = int(engine.prefill_chunk)
        self._estimator = ChunkThroughputEstimator()
        self.tracing = TraceLog(monitor, keep_last=trace_keep_last,
                                clock=clock)
        # crash flight recorder: one bounded ring per replica; the
        # engine shares it (chunk launches/retires, slot patches) so a
        # postmortem covers both planes. Dump path of the most recent
        # crash postmortem, for the fleet router's reroute records.
        self.flight = flight_recorder if flight_recorder is not None \
            else FlightRecorder(label=telemetry_label, clock=clock)
        self.postmortem_path: Optional[str] = None
        if getattr(engine, "flight", None) is None:
            try:
                engine.flight = self.flight
            except (AttributeError, TypeError):
                pass                   # exotic engine stubs: record less
        self._feed_depth = int(feed_depth or engine.max_batch)
        self._idle_wait_s = float(idle_wait_s)
        self._emit_every_s = float(emit_every_s)
        self._last_emit_t = clock()

        self._wake = locks.make_condition("frontend.wake")
        self._cancel_requests: List[StreamHandle] = []
        # (kind, payload, box) migration events the driver thread
        # executes at its next iteration; callers block on box["done"]
        self._migrations: List[tuple] = []
        self._closing = False
        self._closed = False
        self._crashed = False
        # set by FleetRouter.retire_replica: placement has stopped and
        # /readyz reports not-ready so external balancers mirror the
        # router's exclusion while in-engine chunks retire
        self.draining = False
        self._crash_error: Optional[BaseException] = None
        # uid -> handle for requests inside the engine (driver-only)
        self._handles: Dict[int, StreamHandle] = {}
        self.n_submitted = 0

        self._thread = threading.Thread(
            target=self._drive, name="serving-frontend-driver", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- public API
    def submit(self, prompt: Union[Sequence[int], np.ndarray], *,
               priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               trace_id: Optional[str] = None) -> StreamHandle:
        """Enqueue one generation request; returns immediately.

        ``deadline_s`` is a RELATIVE budget ("finish within this many
        seconds"), converted to the absolute clock deadline the scheduler
        tracks. ``slo_ttft_s`` is the TTFT target: it is recorded and
        scored in tracing (``slo_ttft_met``), not enforced — deadlines
        enforce. Rejections (rate limit, pending bound, dead/infeasible
        deadline, closed frontend) resolve the handle to ``rejected``
        with a machine-readable ``reject_reason``; no exception.

        ``trace_id`` is the distributed journey id; minted here when
        the caller (a fleet router) didn't already mint one."""
        now = self._clock()
        trace_id = trace_id or new_trace_id()
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id,
                      deadline_s=(now + deadline_s)
                      if deadline_s is not None else None,
                      trace_id=trace_id, tenant=tenant)
        handle = StreamHandle(req, self, tenant=tenant, priority=priority,
                              slo_ttft_s=slo_ttft_s, submit_t=now,
                              trace_id=trace_id)
        meta = dict(tenant=tenant, priority=priority,
                    prompt_len=req.prompt_len,
                    max_new_tokens=req.max_new_tokens,
                    slo_ttft_s=slo_ttft_s, deadline_s=req.deadline_s,
                    trace_id=trace_id, replica=self._telemetry_label)
        self.n_submitted += 1
        with self._wake:
            dead = self._closing or self._crashed
        if dead:
            self.tracing.record_rejected(req.uid, REJECT_FRONTEND_CLOSED,
                                         **meta)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
            return handle
        ticket = Ticket(prompt_len=req.prompt_len,
                        max_new_tokens=req.max_new_tokens,
                        priority=priority, tenant=tenant,
                        deadline_s=req.deadline_s, slo_ttft_s=slo_ttft_s,
                        payload=handle, trace_id=trace_id)
        handle._ticket = ticket
        reason = self._controller.offer(ticket)
        if reason is not None:
            self.flight.record("reject", uid=req.uid, reason=reason,
                               trace_id=trace_id)
            self.tracing.record_rejected(req.uid, reason, **meta)
            handle._resolve("rejected", reject_reason=reason)
            return handle
        self.flight.record("submit", uid=req.uid, trace_id=trace_id,
                           tenant=tenant, priority=priority,
                           prompt_len=req.prompt_len)
        self.tracing.start(req.uid, **meta)
        self.tracing.mark(req.uid, "submitted", t=now)
        with self._wake:
            self._wake.notify()
        return handle

    def cancel(self, handle: StreamHandle) -> None:
        if handle.done:
            return
        with self._wake:
            self._cancel_requests.append(handle)
            self._wake.notify()

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: stop accepting new work, serve everything
        already accepted to completion, then stop the driver thread.
        Idempotent. After a driver crash this just reaps the thread."""
        with self._wake:
            if self._closed:
                return
            self._closing = True
            self._wake.notify()
        self._thread.join(timeout)
        if self._thread.is_alive():
            logger.warning("serving frontend driver did not drain within "
                           f"{timeout}s; handles may still resolve late")
            return
        # post-join sweep: a submit() that raced the close can leave a
        # ticket the driver never saw
        for ticket in self._controller.drain():
            handle = ticket.payload
            self.tracing.record_rejected(
                handle.uid, REJECT_FRONTEND_CLOSED)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
        self._closed = True
        self.tracing.emit()

    def __enter__(self) -> "ServingFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------------- queries
    @property
    def driver_alive(self) -> bool:
        """The readiness signal ``/readyz`` (health.HealthMonitor) keys
        on: the driver thread is running and has not crashed."""
        return self._thread.is_alive() and not self.crashed

    @property
    def pending_admission(self) -> int:
        return self._controller.pending

    @property
    def max_pending(self) -> int:
        return self._controller.config.max_pending

    @property
    def crashed(self) -> bool:
        with self._wake:
            return self._crashed

    @property
    def crash_error(self) -> Optional[BaseException]:
        with self._wake:
            return self._crash_error

    def load_snapshot(self) -> Dict[str, Any]:
        """Placement inputs for a fleet router: the admission
        controller's and throughput estimator's locked snapshots plus
        the engine backlog. Engine-side numbers are read without the
        driver's cooperation, so they are approximate under concurrency
        — fine for load scoring, not for invariants.

        The dict is ``dstpu-load-v1``: plain ints/floats/strings only,
        so ``json.dumps`` round-trips it losslessly — the transport
        serves it verbatim at ``GET /v1/load``."""
        sched = self._engine.scheduler
        backlog = sum(r.max_new_tokens - len(r.tokens)
                      for r in list(sched.running.values()))
        backlog += sum(q.max_new_tokens + q.prompt_len
                       for q in list(sched.queue))
        return {
            "schema": LOAD_SCHEMA,
            "admission": self._controller.snapshot(),
            "throughput": self._estimator.snapshot(),
            "engine_backlog_tokens": int(backlog),
            "engine_queue_depth": len(sched.queue),
            "engine_running": len(sched.running),
        }

    @staticmethod
    def _handle_snapshot(handle: StreamHandle) -> Dict[str, Any]:
        """One locked read of everything replay (and a postmortem)
        needs about one handle: the ORIGINAL prompt and budget, the
        tokens emitted to the caller so far, and the sampling/admission
        parameters. The shared shape behind ``request_snapshot`` and
        the flight recorder's ``in_flight`` records.

        The dict is ``dstpu-snapshot-v1``: JSON-round-trippable by
        construction — the prompt is a plain int list, never the
        ndarray it used to leak (which ``json.dumps`` rejects), so the
        transport's ``/v1/adopt`` ships it verbatim."""
        with handle._cond:
            emitted = list(handle._tokens)
            status = handle._status or "pending"
        req = handle._request
        return {
            "schema": SNAPSHOT_SCHEMA,
            "uid": handle.uid,
            "trace_id": handle.trace_id,
            "status": status,
            "prompt": [int(t) for t in handle._prompt],
            "prompt_len": int(handle._prompt.shape[0]),
            "tokens_emitted": [int(t) for t in emitted],
            "max_new_tokens": handle._max_new_tokens,
            "sampling": {"eos_token_id": (
                             None if req.eos_token_id is None
                             else int(req.eos_token_id)),
                         "deadline_s": (None if req.deadline_s is None
                                        else float(req.deadline_s)),
                         "priority": int(handle.priority),
                         "tenant": handle.tenant,
                         "slo_ttft_s": (
                             None if handle.slo_ttft_s is None
                             else float(handle.slo_ttft_s))},
        }

    def request_snapshot(self, uid: int) -> Optional[Dict[str, Any]]:
        """Locked accessor for one outstanding request: original prompt,
        tokens emitted so far, and sampling params — the stable API
        replay and postmortems share instead of poking ``_handles``.
        Finds the handle whether it is admission-pending or inside the
        engine; returns None for unknown/finished-and-reaped uids.
        Thread-safe (dict/heap reads are locked or GIL-atomic; the
        token read locks the handle)."""
        handle = self._handles.get(uid)
        if handle is None:
            for ticket in self._controller.tickets():
                payload = ticket.payload
                if payload is not None and payload.uid == uid:
                    handle = payload
                    break
        if handle is None:
            return None
        return self._handle_snapshot(handle)

    def holds_prefix(self, key: bytes) -> bool:
        """Pure prefix-cache membership peek (no LRU touch) — the
        router's placement affinity probe, and the surface the
        transport's ``GET /v1/prefix`` serves. Covers BOTH residency
        levels: the HBM prefix cache and the demoted DRAM/NVMe tier
        (a tier-held prefix still saves the full prefill — it admits
        through an async promotion instead of a recompute). False on
        engines without a prefix cache."""
        kv = getattr(self._engine, "kv", None)
        cache = getattr(kv, "prefix_cache", None)
        if cache is None or not getattr(kv, "prefix_enabled", False):
            return False
        if key in cache:
            return True
        tier = getattr(self._engine, "kv_tier", None)
        return tier is not None and tier.holds(key)

    def fetch_prefix(self, key: bytes) -> Optional[Dict[str, Any]]:
        """Serve a peer's prefix fetch: the demoted entry's KV payload
        as a ``dstpu-prefix-v1`` bundle, or None when this replica does
        not hold it in a FETCHABLE tier. Tier entries only — the HBM
        prefix cache lives in the device pool, which only the engine
        thread may read; a warm prefix becomes fetchable once it
        demotes. Thread-safe (the tier is host-side, lock-protected),
        no driver round-trip."""
        tier = getattr(self._engine, "kv_tier", None)
        if tier is None:
            return None
        return tier.fetch_bundle(key)

    def install_prefix(self, bundle: Dict[str, Any]) -> bool:
        """Install a peer-fetched prefix bundle into the local DRAM
        tier (the receiving half of the distributed prefix cache). The
        entry promotes to HBM through the normal async path when a
        request for its prompt arrives — zero re-prefill. Thread-safe;
        False on engines without a tier or when the tier declined
        (duplicate key / closed)."""
        tier = getattr(self._engine, "kv_tier", None)
        if tier is None:
            return False
        return tier.install_bundle(bundle)

    def migration_candidates(self) -> List[int]:
        """uids of requests movable RIGHT NOW (running, fully
        prefilled, at least one emitted token, paged KV) — the set a
        rebalancer picks from. Thread-safe, approximate under
        concurrency: the driver re-checks at migrate time."""
        eng = self._engine
        can = getattr(eng, "can_migrate", None)
        if can is None:
            return []
        out: List[int] = []
        for req in list(eng.scheduler.running.values()):
            try:
                if req.uid in self._handles and can(req):
                    out.append(int(req.uid))
            except Exception:  # noqa: BLE001 — a racing retire is a no
                continue
        return out

    def migrate_out(self, uid: int, timeout: Optional[float] = 30.0):
        """Serialize and DETACH one running request: returns
        ``(bundle, handle)`` where ``bundle`` is the engine's KV +
        cursor export and ``handle`` is the caller's still-pending
        StreamHandle, released from this frontend (its engine-side
        request is cancelled, its trace segment closes ``migrated``).
        The handle keeps streaming once a target's ``migrate_in``
        re-attaches it. Runs on the driver thread (this call blocks
        until it executes); raises :class:`MigrationError` when the
        request is not migratable or the driver is gone."""
        box: Dict[str, Any] = {"done": threading.Event()}
        with self._wake:
            if self._closing or self._crashed:
                raise MigrationError("frontend is closed or crashed")
            self._migrations.append(("out", {"uid": int(uid)}, box))
            self._wake.notify()
        if not box["done"].wait(timeout):
            raise MigrationError(
                f"migrate_out uid={uid} did not execute within "
                f"{timeout}s")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["bundle"], box["handle"]

    def migrate_in(self, bundle: Dict[str, Any],
                   handle: Optional[StreamHandle] = None, *,
                   migrated_from: Optional[str] = None,
                   timeout: Optional[float] = 30.0) -> StreamHandle:
        """Re-home an exported request HERE, mid-decode: lease blocks,
        scatter the bundle's KV, and join the running set — the next
        chunk continues from the migrated cursor, greedy bit-identical
        to never having moved. ``handle`` (the in-process case) is
        re-attached and keeps streaming to its caller; without one (the
        transport server case) a fresh handle is built whose delivered
        prefix is the bundle's resumed tokens. Raises
        :class:`MigrationError` when this engine cannot host the
        request (the caller re-imports at the source)."""
        box: Dict[str, Any] = {"done": threading.Event()}
        with self._wake:
            if self._closing or self._crashed:
                raise MigrationError("frontend is closed or crashed")
            self._migrations.append(
                ("in", {"bundle": bundle, "handle": handle,
                        "migrated_from": migrated_from}, box))
            self._wake.notify()
        if not box["done"].wait(timeout):
            raise MigrationError(
                f"migrate_in did not execute within {timeout}s")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["handle"]

    def stats(self) -> Dict[str, Any]:
        """Control-plane counters (thread-safe, approximate under
        concurrency)."""
        return {
            "submitted": self.n_submitted,
            "pending_admission": self._controller.pending,
            "offered": self._controller.n_offered,
            "rate_limited": self._controller.n_rate_limited,
            "shed": self._controller.n_shed,
            "decode_rate_tokens_per_s": self._estimator.rate(),
            "terminal": dict(self.tracing.counters),
        }

    def drain_pending(self) -> List[StreamHandle]:
        """Graceful drain, phase one: pull every admission-pending
        ticket off this frontend (thread-safe) and return the still-live
        handles so a router can re-home them on survivors. Requests
        already inside the engine are NOT touched — their chunks retire
        naturally, which is the rest of the drain. Each returned
        handle's trace segment here closes ``rerouted``; the adopter
        re-opens the same uid/trace_id."""
        handles: List[StreamHandle] = []
        for ticket in self._controller.drain():
            handle: StreamHandle = ticket.payload
            if handle is None or handle.done:
                continue
            self.tracing.finish(handle.uid, "rerouted")
            handles.append(handle)
        return handles

    def adopt(self, handle: StreamHandle,
              rerouted_from: Optional[str] = None) -> bool:
        """Re-home a handle from a crashed or draining peer onto this
        frontend. The SAME StreamHandle keeps streaming to its caller;
        only the backend changes — the handle keeps its ``trace_id``,
        and this replica's trace segment records
        ``rerouted_from=<source replica>`` so the journey stays one
        connected story.

        Never-prefilled handles restart from scratch. Handles that
        already streamed tokens are REPLAYED: this engine re-prefills
        the original prompt + the tokens already emitted (a paged
        ``PrefixCache`` hit when a peer replayed the same stream), the
        token budget shrinks by the emitted count, and the delivery
        cursor resets so ``_push_progress`` hands the caller only
        freshly generated tokens — zero duplicates, greedy
        bit-identical to an uncrashed run. The replay is rebuilt from
        the handle's ORIGINAL prompt/budget each time, so repeated
        crashes compose. The survivor's ``submitted`` trace mark keeps
        the ORIGINAL submit time: a journey's latency clock never
        resets, so recovery delay lands in TTFT/queue-wait SLOs.

        Returns False — after resolving the handle ``rejected`` — when
        this frontend cannot take it; thread-safe."""
        if handle.done:
            return False
        req = handle._request
        emitted = handle.tokens
        n_emitted = len(emitted)
        if req.status == "done" or n_emitted >= handle._max_new_tokens \
                or (req.eos_token_id is not None
                    and n_emitted and emitted[-1] == req.eos_token_id):
            # the stream already delivered its full output — the crash
            # only stole the final status. Nothing to replay: close the
            # journey here as done.
            self.tracing.start(req.uid, trace_id=handle.trace_id,
                               replica=self._telemetry_label,
                               rerouted_from=rerouted_from)
            self.tracing.finish(req.uid, "done")
            handle._resolve("done")
            return True
        # rebuild the scheduler-side lifecycle from the handle's
        # original prompt/budget: replay prompt = prompt + emitted
        # prefix, remaining budget = original budget - emitted count
        req.prompt = handle._prompt
        req.max_new_tokens = handle._max_new_tokens
        if n_emitted:
            req.prompt = np.concatenate(
                [handle._prompt, np.asarray(emitted, np.int32)])
            req.max_new_tokens = handle._max_new_tokens - n_emitted
        req.tokens = []
        req.status = "new"
        req.slot = None
        req.submit_t = None
        req.first_token_t = None
        req.finish_t = None
        req.tenant = handle.tenant
        handle._pushed = 0
        handle._prefill_marked = False
        handle._frontend = self
        meta = dict(tenant=handle.tenant, priority=handle.priority,
                    prompt_len=req.prompt_len,
                    max_new_tokens=req.max_new_tokens,
                    slo_ttft_s=handle.slo_ttft_s, deadline_s=req.deadline_s,
                    trace_id=handle.trace_id,
                    replica=self._telemetry_label,
                    rerouted_from=rerouted_from,
                    replayed_tokens=n_emitted)
        self.n_submitted += 1
        with self._wake:
            dead = self._closing or self._crashed
        if dead:
            self.tracing.record_rejected(req.uid, REJECT_FRONTEND_CLOSED,
                                         **meta)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
            return False
        ticket = Ticket(prompt_len=req.prompt_len,
                        max_new_tokens=req.max_new_tokens,
                        priority=handle.priority, tenant=handle.tenant,
                        deadline_s=req.deadline_s,
                        slo_ttft_s=handle.slo_ttft_s, payload=handle,
                        trace_id=handle.trace_id)
        handle._ticket = ticket
        reason = self._controller.offer(ticket)
        if reason is not None:
            self.tracing.record_rejected(req.uid, reason, **meta)
            handle._resolve("rejected", reject_reason=reason)
            return False
        self.flight.record("adopt", uid=req.uid,
                           trace_id=handle.trace_id,
                           rerouted_from=rerouted_from,
                           replayed_tokens=n_emitted)
        self.tracing.start(req.uid, **meta)
        self.tracing.mark(req.uid, "submitted", t=handle.submit_t)
        with self._wake:
            self._wake.notify()
        return True

    # ------------------------------------------------------ driver loop
    def _drive(self) -> None:
        try:
            with telemetry.replica_label(self._telemetry_label):
                while self._drive_once():
                    pass
        except BaseException as e:  # noqa: BLE001 — converted to results
            self._fail_all(e)

    def _drive_once(self) -> bool:
        eng = self._engine
        with self._wake:
            if not (self._cancel_requests or self._migrations
                    or self._closing or self._controller.pending
                    or eng.scheduler.has_work() or eng.chunk_in_flight):
                self._wake.wait(self._idle_wait_s)
            cancels, self._cancel_requests = self._cancel_requests, []
            migrations, self._migrations = self._migrations, []
            closing = self._closing
        for handle in cancels:
            self._do_cancel(handle)
        for kind, payload, box in migrations:
            try:
                if kind == "out":
                    self._do_migrate_out(payload["uid"], box)
                else:
                    self._do_migrate_in(payload["bundle"],
                                        payload["handle"],
                                        payload["migrated_from"], box)
            except Exception as e:  # noqa: BLE001 — caller unblocks
                box["error"] = f"{type(e).__name__}: {e}"
            finally:
                box["done"].set()
        self._feed()
        if eng.scheduler.has_work() or eng.chunk_in_flight:
            tokens_before = eng.metrics.tokens_out
            inline_before = getattr(eng, "inline_prefill_tokens", 0)
            t0 = time.perf_counter()
            finished = eng.pump()
            dt = time.perf_counter() - t0
            produced = eng.metrics.tokens_out - tokens_before
            chunk = self._controller.config.fused_prefill_chunk
            if chunk:
                # inline prompt chunks consume scan steps exactly like
                # decode tokens do: fold them into the throughput EWMA
                # in the same decode-token-equivalent unit the cost
                # model bills, or a prefill-heavy chunk would read as a
                # throughput collapse and shed feasible deadlines
                inline = getattr(eng, "inline_prefill_tokens", 0) \
                    - inline_before
                if inline > 0:
                    produced += -(-inline // chunk)
            self._estimator.record(produced, dt)
            rate = self._estimator.rate()
            if rate is not None:
                telemetry.gauge("admission/ewma_tokens_per_s", float(rate))
            telemetry.gauge("frontend/queue_depth",
                            float(self._controller.pending))
            self._deliver(finished)
            # the scheduler's finished list is an append-only log; the
            # frontend is its only consumer, so trim it here or a
            # long-running server grows without bound
            eng.scheduler.finished.clear()
        self._maybe_emit()
        if closing:
            # a caller may have appended a cancel since the drain above
            # dropped the wake lock — re-check under it before exiting
            with self._wake:
                cancels_drained = not self._cancel_requests
            if cancels_drained and not (self._controller.pending
                                        or eng.scheduler.has_work()
                                        or eng.chunk_in_flight
                                        or self._handles):
                return False
        return True

    def _feed(self) -> None:
        """Move admission winners into the engine scheduler, keeping its
        FIFO at most ``feed_depth`` deep so priority order keeps ruling
        the backlog."""
        eng = self._engine
        sched = eng.scheduler
        room = self._feed_depth - len(sched.queue)
        if room <= 0 or self._controller.pending == 0:
            return
        cfg = self._controller.config
        backlog = sum(r.max_new_tokens - len(r.tokens)
                      for r in sched.running.values())
        chunk = cfg.fused_prefill_chunk
        if chunk:
            backlog += sum(
                q.max_new_tokens + -(-q.prompt_len // chunk)
                for q in sched.queue)
            # mid-prompt lanes still owe their remaining inline chunks
            # before they emit a single decode token
            for slot, done in getattr(eng, "_pf_consumed", {}).items():
                req = sched.running.get(slot)
                if req is not None and done < req.prompt_len:
                    backlog += -(-(req.prompt_len - done) // chunk)
        else:
            w = cfg.prefill_token_weight
            backlog += sum(q.max_new_tokens + q.prompt_len * w
                           for q in sched.queue)
        admits, sheds = self._controller.pop(
            room=room, rate=self._estimator.rate(), backlog_tokens=backlog)
        for ticket, reason in sheds:
            self.flight.record("shed", uid=ticket.payload.uid,
                               reason=reason, trace_id=ticket.trace_id)
            self._resolve_rejected(ticket, reason)
        for ticket in admits:
            handle: StreamHandle = ticket.payload
            req = handle._request
            eng.submit(req)
            if req.status == "rejected":      # scheduler-side reject
                self._resolve_rejected(ticket, req.reject_reason)
            else:
                self._handles[req.uid] = handle
                self.flight.record("admit", uid=req.uid,
                                   trace_id=ticket.trace_id)
                self.tracing.mark(req.uid, "admitted")

    def _resolve_rejected(self, ticket: Ticket, reason: str) -> None:
        handle: StreamHandle = ticket.payload
        self.tracing.finish(handle.uid, "rejected", reject_reason=reason)
        handle._resolve("rejected", reject_reason=reason)

    def _push_progress(self, req: Request,
                       handle: Optional[StreamHandle] = None) -> None:
        handle = handle or self._handles.get(req.uid)
        if handle is None:
            return
        if not handle._prefill_marked and req.first_token_t is not None:
            # prefill completion = the first sampled token's scheduler
            # timestamp (same monotonic timebase as the frontend clock)
            self.tracing.mark(req.uid, "prefill", t=req.first_token_t)
            handle._prefill_marked = True
        n = len(req.tokens)
        if n > handle._pushed:
            new = req.tokens[handle._pushed:n]
            handle._pushed = n
            self.tracing.chunk(req.uid, len(new))
            handle._push(new)

    def _deliver(self, finished: List[Request]) -> None:
        eng = self._engine
        for req in list(eng.scheduler.running.values()):
            self._push_progress(req)
        for req in finished:
            handle = self._handles.pop(req.uid, None)
            if handle is None:
                continue              # cancelled earlier this iteration
            self._push_progress(req, handle)
            self.tracing.finish(req.uid, req.status)
            handle._resolve(req.status)

    def _do_migrate_out(self, uid: int, box: Dict[str, Any]) -> None:
        """Driver-side half of :meth:`migrate_out`: flush delivered
        tokens (the handle's emitted prefix must equal the request's
        committed tokens — the bundle's resumed-token count), export
        the KV bundle, then detach: pop the handle, cancel the
        engine-side request (slot + blocks free within this
        iteration), and close the trace segment ``migrated``."""
        eng = self._engine
        handle = self._handles.get(uid)
        if handle is None:
            box["error"] = f"uid {uid} is not inside this engine"
            return
        req = handle._request
        self._push_progress(req, handle)
        bundle = eng.export_request(req)       # raises MigrationError
        self._handles.pop(uid, None)
        eng.cancel(req)
        self.flight.record("migrate_out", uid=uid,
                           trace_id=handle.trace_id,
                           n_tokens=len(bundle["tokens"]),
                           kv_bytes=bundle["kv_bytes"])
        self.tracing.finish(uid, "migrated")
        box["bundle"] = bundle
        box["handle"] = handle

    def _do_migrate_in(self, bundle: Dict[str, Any],
                       handle: Optional[StreamHandle],
                       migrated_from: Optional[str],
                       box: Dict[str, Any]) -> None:
        """Driver-side half of :meth:`migrate_in`: import the bundle
        into the engine (slot + blocks + cursor), then attach the
        caller's handle (or mint one for a transport-server stream) so
        delivery resumes exactly past the resumed-token prefix."""
        eng = self._engine
        req = eng.import_request(bundle)       # raises MigrationError
        resumed = len(req.tokens)
        if handle is None:
            handle = StreamHandle(
                req, self, tenant=req.tenant, priority=PRIORITY_NORMAL,
                slo_ttft_s=None, submit_t=self._clock(),
                trace_id=req.trace_id)
            with handle._cond:
                # the resumed prefix was already delivered at the
                # source; keep it in the buffer so absolute token
                # indices (the wire's dedup key) stay continuous, and
                # park the cursor past it so a server-side stream
                # starts at the first fresh token
                handle._tokens = [int(t) for t in req.tokens]
                handle._cursor = resumed
        handle._request = req
        handle._frontend = self
        handle._ticket = None
        handle._pushed = resumed
        handle._prefill_marked = True
        self._handles[req.uid] = handle
        self.n_submitted += 1
        meta = dict(tenant=handle.tenant, priority=handle.priority,
                    prompt_len=req.prompt_len,
                    max_new_tokens=req.max_new_tokens,
                    slo_ttft_s=handle.slo_ttft_s,
                    deadline_s=req.deadline_s,
                    trace_id=handle.trace_id,
                    replica=self._telemetry_label,
                    migrated_from=migrated_from,
                    resumed_tokens=resumed)
        self.tracing.start(req.uid, **meta)
        self.tracing.mark(req.uid, "submitted", t=handle.submit_t)
        self.tracing.mark(req.uid, "admitted")
        self.flight.record("migrate_in", uid=req.uid,
                           trace_id=handle.trace_id,
                           migrated_from=migrated_from,
                           resumed_tokens=resumed)
        box["handle"] = handle

    def _do_cancel(self, handle: StreamHandle) -> None:
        if handle.done:
            return
        self.flight.record("cancel", uid=handle.uid,
                           trace_id=handle.trace_id)
        ticket = handle._ticket
        if ticket is not None and self._controller.remove(ticket):
            # never reached the engine: no slot, no device work
            self.tracing.finish(handle.uid, "cancelled")
            handle._resolve("cancelled")
            return
        req = handle._request
        if self._engine.cancel(req):
            self._handles.pop(req.uid, None)
            self._push_progress(req, handle)
            self.tracing.finish(handle.uid, "cancelled")
            handle._resolve("cancelled")
        # else: the request reached a terminal state in the scheduler
        # already — the regular _deliver path resolves the handle

    def _maybe_emit(self) -> None:
        now = self._clock()
        if now - self._last_emit_t >= self._emit_every_s:
            self._last_emit_t = now
            sched = getattr(self._engine, "scheduler", None)
            self.flight.record(
                "snapshot",
                pending_admission=self._controller.pending,
                queue_depth=len(sched.queue) if sched is not None else 0,
                running=len(sched.running) if sched is not None else 0,
                handles=len(self._handles))
            self.tracing.emit()

    def _fail_all(self, exc: BaseException) -> None:
        """Driver crash: every outstanding request — pending admission,
        queued, running — either reroutes to a survivor or resolves to a
        structured ``error`` result so no caller blocks forever, then
        the frontend is marked dead (new submits reject with
        ``frontend_closed``).

        With an ``on_crash`` hook installed, EVERY live handle is
        salvageable: admission-pending and engine-queued requests
        restart from scratch on a survivor, and requests that already
        prefilled or streamed tokens are REPLAYED — the handle carries
        the original prompt plus every emitted token, which is all a
        survivor's ``adopt()`` needs to re-prefill and resume the
        stream with zero duplicates (the device KV died with the
        replica; the journey did not). Only cancel-pending handles are
        excluded — the caller already gave up on them.

        Before resolving ANYTHING the flight recorder dumps a
        postmortem (``self.postmortem_path``) whose ``in_flight`` list
        is exactly the handle set this crash is about to hand off for
        reroute or resolve ``error``."""
        msg = f"{type(exc).__name__}: {exc}"
        logger.error(f"serving frontend driver crashed: {msg}")
        with self._wake:
            self._crashed = True
            self._crash_error = exc
            cancels, self._cancel_requests = self._cancel_requests, []
            migrations, self._migrations = self._migrations, []
        for _kind, _payload, box in migrations:
            box["error"] = f"driver crashed: {msg}"
            box["done"].set()
        cancel_uids = {h.uid for h in cancels}
        salvaged: List[StreamHandle] = []
        for ticket in self._controller.drain():
            if ticket.payload.uid not in cancel_uids:
                salvaged.append(ticket.payload)
        # engine-queued requests were fed but never admitted to a slot:
        # host-only state, safe to replay elsewhere (scheduler data is
        # driver-owned and this IS the driver thread, post-crash)
        sched = getattr(self._engine, "scheduler", None)
        if sched is not None:
            for req in list(sched.queue):
                handle = self._handles.pop(req.uid, None)
                if handle is not None and handle.uid not in cancel_uids:
                    salvaged.append(handle)
            sched.queue.clear()
        # running handles (admitted, possibly mid-stream): flush any
        # recorded-but-unpushed tokens first so the handle's emitted
        # prefix matches what the device actually committed — the
        # replay prompt is built from exactly this prefix
        running: List[StreamHandle] = []
        for uid, handle in list(self._handles.items()):
            try:
                self._push_progress(handle._request, handle)
            except Exception:  # noqa: BLE001 — salvage beats bookkeeping
                pass
            if uid not in cancel_uids:
                running.append(handle)
        # ---- postmortem: capture the in-flight set pre-resolution ----
        in_flight: List[Dict[str, Any]] = []
        seen: set = set()
        for disposition, group in (("salvageable", salvaged),
                                   ("salvageable", running),
                                   ("cancel_pending", cancels)):
            for handle in group:
                if handle.uid in seen:
                    continue
                seen.add(handle.uid)
                in_flight.append({
                    "uid": handle.uid,
                    "trace_id": handle.trace_id,
                    "status": handle.status,
                    "n_tokens": len(handle.tokens),
                    "prompt_len": int(handle._prompt.shape[0]),
                    "max_new_tokens": handle._max_new_tokens,
                    "disposition": disposition})
        slot_uids = {}
        if sched is not None:
            slot_uids = {req.slot: req.uid
                         for req in list(sched.running.values())
                         if req.slot is not None}
        try:
            self.postmortem_path = self.flight.dump(
                reason="driver_crash", error=msg, in_flight=in_flight,
                slot_uids=slot_uids,
                extra={"n_salvageable": len(salvaged) + len(running),
                       "n_running": len(running),
                       "pending_admission": self._controller.pending})
        except Exception as dump_exc:  # noqa: BLE001 — never block drain
            logger.error(f"flight recorder dump failed: {dump_exc}")
        # hand never-prefilled work first: survivors fill slots with
        # cheap restarts while the replays re-prefill behind them
        to_hand: List[StreamHandle] = salvaged + running
        handed: List[StreamHandle] = []
        if self._on_crash is not None and to_hand:
            try:
                handed = list(to_hand)
                self._on_crash(self, list(to_hand), exc)
                to_hand = []
            except Exception as hook_exc:  # noqa: BLE001 — fall back
                handed = []
                logger.error(
                    f"crash re-route hook failed ({hook_exc}); resolving "
                    f"{len(to_hand)} salvaged handles as error")
        # close this replica's trace segment for every handle the hook
        # re-homed: terminal status ``rerouted`` links the journey's next
        # segment (the survivor re-opens the same uid/trace_id)
        for handle in handed:
            self.tracing.finish(handle.uid, "rerouted", error=msg)
        for handle in to_hand:
            self.tracing.finish(handle.uid, "error", error=msg)
            handle._resolve("error", error=msg)
        self._handles.clear()
        for handle in cancels:
            self.tracing.finish(handle.uid, "error", error=msg)
            handle._resolve("error", error=msg)
