"""Per-request tracing for the serving frontend.

``ServingMetrics`` (serving/metrics.py) aggregates engine-side counters;
this module records the *per-request* control-plane story the frontend
owns: a span record per request

    submitted -> admitted -> prefill -> first_token -> chunk[i] -> finish

with derived latency stats (TTFT, TPOT, queue wait) folded into
reservoir-backed p50/p95/p99 histograms (the same ``Reservoir`` the
engine metrics use). Snapshots emit through the existing monitor fan-out
(``(label, value, sample)`` events — CSV/TensorBoard/W&B pick them up
unchanged) and the whole log dumps as JSON for offline analysis
(``frontend_bench.py`` embeds it in ``BENCH_frontend.json``).

Latency fields (all seconds):
  ttft_s        submit -> first streamed token (the user-visible TTFT —
                measured from ``ServingFrontend.submit``, so it includes
                admission queueing, unlike the engine's scheduler-side
                TTFT)
  queue_wait_s  submit -> prefill start (time spent waiting for
                admission + a slot)
  tpot_s        mean time per output token after the first
                (first_token -> finish over n_tokens - 1)

Thread safety: one lock around all mutation — marks arrive from the
frontend driver thread while ``snapshot``/``to_json`` may be read from
callers.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional

from ...analysis import locks
from ..metrics import Reservoir
from ...telemetry.core import count as _telemetry_count
from ...telemetry.core import gauge as _telemetry_gauge

#: canonical span event names, in lifecycle order
EVENTS = ("submitted", "admitted", "prefill", "first_token", "finish")

#: /tenants payload schema
TENANTS_SCHEMA = "dstpu-tenants-v1"


class _TenantStats:
    """Per-tenant terminal aggregates. Goodput counts the tokens of
    requests that finished ``done`` without missing their TTFT SLO —
    requests with no SLO set count as good (delivered tokens with no
    target are not a miss), so untargeted traffic never reads as zero
    goodput."""

    __slots__ = ("counts", "total_tokens", "goodput_tokens",
                 "n_slo_scored", "n_slo_met", "ttft", "tpot")

    def __init__(self, reservoir_capacity: int):
        self.counts: Dict[str, int] = {}
        self.total_tokens = 0
        self.goodput_tokens = 0
        self.n_slo_scored = 0
        self.n_slo_met = 0
        self.ttft = Reservoir(reservoir_capacity)
        self.tpot = Reservoir(reservoir_capacity)

    def fold(self, trace: "RequestTrace") -> None:
        status = trace.status or "unknown"
        self.counts[status] = self.counts.get(status, 0) + 1
        self.total_tokens += trace.n_tokens
        met = trace.slo_ttft_met
        if met is not None:
            self.n_slo_scored += 1
            self.n_slo_met += int(met)
        if status == "done" and met is not False:
            self.goodput_tokens += trace.n_tokens
        if trace.ttft_s is not None:
            self.ttft.add(trace.ttft_s)
        if trace.tpot_s is not None:
            self.tpot.add(trace.tpot_s)

    @property
    def goodput_fraction(self) -> float:
        if self.total_tokens <= 0:
            return 1.0
        return self.goodput_tokens / self.total_tokens

    def to_dict(self) -> Dict[str, Any]:
        return {
            "requests": dict(self.counts),
            "n_requests": sum(self.counts.values()),
            "total_tokens": self.total_tokens,
            "goodput_tokens": self.goodput_tokens,
            "goodput_fraction": self.goodput_fraction,
            "slo": {"scored": self.n_slo_scored,
                    "met": self.n_slo_met},
            "ttft_s": {"p50": self.ttft.percentile(50),
                       "p95": self.ttft.percentile(95),
                       "n": self.ttft.n_seen},
            "tpot_s": {"p50": self.tpot.percentile(50),
                       "p95": self.tpot.percentile(95),
                       "n": self.tpot.n_seen},
        }


class RequestTrace:
    """One request's span record. ``events`` maps event name -> absolute
    clock time; chunk deliveries append to ``chunks`` as (t, n_tokens)
    pairs rather than one event each (a 512-token stream stays a compact
    record)."""

    __slots__ = ("uid", "tenant", "priority", "prompt_len",
                 "max_new_tokens", "slo_ttft_s", "deadline_s", "events",
                 "chunks", "status", "reject_reason", "error", "n_tokens",
                 "trace_id", "replica", "rerouted_from", "replayed_tokens",
                 "migrated_from", "resumed_tokens")

    def __init__(self, uid: int, *, tenant: str = "default",
                 priority: int = 1, prompt_len: int = 0,
                 max_new_tokens: int = 0,
                 slo_ttft_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 replica: Optional[str] = None,
                 rerouted_from: Optional[str] = None,
                 replayed_tokens: int = 0,
                 migrated_from: Optional[str] = None,
                 resumed_tokens: int = 0):
        self.uid = uid
        self.tenant = tenant
        self.priority = priority
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.slo_ttft_s = slo_ttft_s
        self.deadline_s = deadline_s
        # fleet journey identity: the distributed trace id this request
        # rides under, which replica recorded this segment, and — for a
        # segment re-homed after a crash — the replica it came from
        self.trace_id = trace_id
        self.replica = replica
        self.rerouted_from = rerouted_from
        # tokens the caller had ALREADY received when this segment
        # opened: >0 marks an in-flight replay after a crash (the
        # survivor re-prefilled prompt + this many emitted tokens)
        self.replayed_tokens = replayed_tokens
        # live KV-block migration hop: the replica this segment's KV
        # arrived from, and the decode cursor it resumed at (no
        # re-prefill — the blocks moved, unlike a crash replay)
        self.migrated_from = migrated_from
        self.resumed_tokens = resumed_tokens
        self.events: Dict[str, float] = {}
        self.chunks: List[List[float]] = []      # [t, n_tokens] pairs
        self.status: Optional[str] = None        # terminal status
        self.reject_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.n_tokens = 0

    # ------------------------------------------------------- derived
    def _delta(self, a: str, b: str) -> Optional[float]:
        if a in self.events and b in self.events:
            return self.events[b] - self.events[a]
        return None

    @property
    def ttft_s(self) -> Optional[float]:
        return self._delta("submitted", "first_token")

    @property
    def queue_wait_s(self) -> Optional[float]:
        return self._delta("submitted", "prefill")

    @property
    def tpot_s(self) -> Optional[float]:
        dt = self._delta("first_token", "finish")
        if dt is None or self.n_tokens < 2:
            return None
        return dt / (self.n_tokens - 1)

    @property
    def slo_ttft_met(self) -> Optional[bool]:
        """Whether the measured TTFT met the request's SLO target; None
        when no target was set or no token was produced."""
        if self.slo_ttft_s is None or self.ttft_s is None:
            return None
        return self.ttft_s <= self.slo_ttft_s

    def to_dict(self) -> Dict[str, Any]:
        return {
            "uid": self.uid,
            "trace_id": self.trace_id,
            "replica": self.replica,
            "rerouted_from": self.rerouted_from,
            "replayed_tokens": self.replayed_tokens,
            "migrated_from": self.migrated_from,
            "resumed_tokens": self.resumed_tokens,
            "tenant": self.tenant,
            "priority": self.priority,
            "prompt_len": self.prompt_len,
            "max_new_tokens": self.max_new_tokens,
            "status": self.status,
            "reject_reason": self.reject_reason,
            "error": self.error,
            "n_tokens": self.n_tokens,
            "slo_ttft_s": self.slo_ttft_s,
            "deadline_s": self.deadline_s,
            "events": dict(self.events),
            "chunks": [list(c) for c in self.chunks],
            "ttft_s": self.ttft_s,
            "queue_wait_s": self.queue_wait_s,
            "tpot_s": self.tpot_s,
            "slo_ttft_met": self.slo_ttft_met,
        }


class TraceLog:
    """Bounded per-request span store + latency histograms + terminal
    counters, with monitor fan-out emission.

    ``keep_last`` bounds the retained *finished* span records (the
    histograms and counters keep aggregating past it — a long-running
    server never grows unboundedly)."""

    #: histogram name -> RequestTrace property feeding it
    _HISTOGRAMS = ("ttft_s", "tpot_s", "queue_wait_s")

    def __init__(self, monitor=None, *, keep_last: int = 256,
                 reservoir_capacity: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        self.monitor = monitor
        self.clock = clock
        self.keep_last = int(keep_last)
        self._lock = locks.make_lock("frontend.tracelog")
        self._live: "OrderedDict[int, RequestTrace]" = OrderedDict()
        self._done: Deque[RequestTrace] = deque(maxlen=self.keep_last)
        self.histograms: Dict[str, Reservoir] = {
            name: Reservoir(reservoir_capacity)
            for name in self._HISTOGRAMS}
        self.counters: Dict[str, int] = {}
        # per-tenant goodput/latency aggregates, keyed by the tenant
        # label each trace carries (untagged records fold under
        # "default" — aggregation never silently drops them)
        self._reservoir_capacity = int(reservoir_capacity)
        self._tenants: Dict[str, _TenantStats] = {}
        self._emit_seq = 0
        # terminal-record fan-out (SLO engine): called OUTSIDE the lock
        self._listeners: List[Callable[[RequestTrace], None]] = []

    def add_listener(self,
                     fn: Callable[["RequestTrace"], None]) -> None:
        """Subscribe to every terminal record (``finish`` /
        ``record_rejected``). Listeners run on the finishing thread
        after the log's lock is released — they may read the trace but
        must not call back into this log."""
        self._listeners.append(fn)

    # ---------------------------------------------------------- recording
    def start(self, uid: int, **meta) -> RequestTrace:
        """Open a span (event ``submitted`` stamped now unless an
        explicit time is threaded via ``mark`` later)."""
        trace = RequestTrace(uid, **meta)
        with self._lock:
            self._live[uid] = trace
        return trace

    def mark(self, uid: int, event: str,
             t: Optional[float] = None) -> None:
        with self._lock:
            trace = self._live.get(uid)
            if trace is not None and event not in trace.events:
                trace.events[event] = self.clock() if t is None else t

    def chunk(self, uid: int, n_tokens: int,
              t: Optional[float] = None) -> None:
        """One delivery of ``n_tokens`` streamed tokens (one decode chunk
        retiring). The first delivery also stamps ``first_token``."""
        with self._lock:
            trace = self._live.get(uid)
            if trace is None or n_tokens <= 0:
                return
            now = self.clock() if t is None else t
            if "first_token" not in trace.events:
                trace.events["first_token"] = now
            trace.chunks.append([now, int(n_tokens)])
            trace.n_tokens += int(n_tokens)

    def finish(self, uid: int, status: str, *,
               reject_reason: Optional[str] = None,
               error: Optional[str] = None,
               t: Optional[float] = None) -> Optional[RequestTrace]:
        """Close a span with its terminal status; folds its latencies
        into the histograms and bumps the terminal counters. Terminal
        listeners (``add_listener``) fire after the lock is released."""
        with self._lock:
            trace = self._live.pop(uid, None)
            if trace is None:
                return None
            trace.events["finish"] = self.clock() if t is None else t
            trace.status = status
            trace.reject_reason = reject_reason
            trace.error = error
            self.counters[status] = self.counters.get(status, 0) + 1
            if reject_reason:
                key = f"rejected:{reject_reason}"
                self.counters[key] = self.counters.get(key, 0) + 1
            met = trace.slo_ttft_met
            if met is not None:
                key = "slo_ttft_met" if met else "slo_ttft_missed"
                self.counters[key] = self.counters.get(key, 0) + 1
            for name in self._HISTOGRAMS:
                v = getattr(trace, name)
                if v is not None:
                    self.histograms[name].add(v)
            tenant = getattr(trace, "tenant", None) or "default"
            stats = self._tenants.get(tenant)
            if stats is None:
                stats = self._tenants[tenant] = _TenantStats(
                    self._reservoir_capacity)
            stats.fold(trace)
            goodput = stats.goodput_fraction
            self._done.append(trace)
        # tenant-labelled series on /metrics: the embedded-label names
        # ride the same split_embedded_labels mechanism replica labels
        # use (and compose with them — name|tenant=a|replica=0)
        _telemetry_gauge(f"frontend/goodput_fraction|tenant={tenant}",
                         float(goodput))
        if trace.n_tokens:
            _telemetry_count(f"frontend/tenant_tokens|tenant={tenant}",
                             float(trace.n_tokens))
        for fn in self._listeners:
            try:
                fn(trace)
            except Exception:  # noqa: BLE001 — observers never break us
                pass
        return trace

    def record_rejected(self, uid: int, reason: str, **meta) -> None:
        """Shorthand for a request rejected before it ever opened a live
        span (submit-side gate rejections)."""
        self.start(uid, **meta)
        self.mark(uid, "submitted")
        self.finish(uid, "rejected", reject_reason=reason)

    # ------------------------------------------------------------ reading
    def snapshot(self) -> Dict[str, float]:
        """Flat label -> value map (the monitor event payload)."""
        with self._lock:
            out: Dict[str, float] = {}
            for name, res in self.histograms.items():
                pct = res.percentiles((50, 95, 99))
                base = name[:-2] if name.endswith("_s") else name
                out[f"frontend/{base}_p50_s"] = pct[50]
                out[f"frontend/{base}_p95_s"] = pct[95]
                out[f"frontend/{base}_p99_s"] = pct[99]
            for status, n in self.counters.items():
                out[f"frontend/{status.replace(':', '_')}"] = float(n)
            return out

    def histogram_stats(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, Any]:
        """Locked snapshot of the latency histograms for exposition:
        name -> {quantiles: {q: value}, count, sum}. Computed entirely
        under the lock so a concurrent ``finish`` never mutates a
        reservoir mid-serialization."""
        with self._lock:
            return {name: {"quantiles": {q: res.percentile(q * 100)
                                         for q in qs},
                           "count": res.n_seen,
                           "sum": res.total}
                    for name, res in self.histograms.items()}

    def counter_totals(self) -> Dict[str, int]:
        """Locked copy of the terminal-status counters."""
        with self._lock:
            return dict(self.counters)

    def tenants_report(self) -> Dict[str, Any]:
        """Per-tenant goodput accounting (the ``/tenants`` endpoint
        payload): terminal counts, tokens delivered within SLO vs
        total, and TTFT/TPOT reservoir percentiles per tenant."""
        # per-tenant stats keep mutating under finish(): rendering
        # INSIDE the lock is what makes each tenant row self-consistent
        # (lockcheck-audited; the row count is small and bounded)
        with self._lock:
            tenants = {t: s.to_dict()
                       for t, s in sorted(self._tenants.items())}
        return {
            "schema": TENANTS_SCHEMA,
            "n_tenants": len(tenants),
            "tenants": tenants,
        }

    def emit(self, sample: Optional[int] = None) -> Dict[str, float]:
        """Write the snapshot through the monitor fan-out (no-op without
        a monitor; still returns the snapshot)."""
        snap = self.snapshot()
        if self.monitor is not None:
            self._emit_seq = self._emit_seq + 1 if sample is None \
                else int(sample)
            self.monitor.write_events(
                [(label, value, self._emit_seq)
                 for label, value in snap.items()])
        return snap

    def to_json(self) -> Dict[str, Any]:
        # copy-out under the lock, render outside it: ``_done`` traces
        # are terminal (finish() moved them here and nothing mutates
        # them again), so their to_dict() — the bulk of this payload —
        # must not hold up every concurrent finish()/start(). Only the
        # still-mutating pieces (histograms, counters, _live) serialize
        # under the lock, where rendering IS the consistency guarantee.
        with self._lock:
            done = list(self._done)
            histograms = {
                name: {
                    "p50": res.percentile(50),
                    "p95": res.percentile(95),
                    "p99": res.percentile(99),
                    "n": res.n_seen,
                } for name, res in self.histograms.items()}
            counters = dict(self.counters)
            live = [t.to_dict() for t in self._live.values()]
        return {
            "histograms": histograms,
            "counters": counters,
            "requests": [t.to_dict() for t in done],
            "live": live,
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    def export_chrome(self, path: Optional[str] = None,
                      runtime=None) -> Dict[str, Any]:
        """One Perfetto file for the whole story: this log's per-request
        lanes (with submit->finish flow arrows) merged with the
        process-wide telemetry runtime's engine/driver timeline — no
        second trace format to maintain. On Linux the two clocks
        (``time.monotonic`` here, ``time.perf_counter`` in telemetry)
        are both CLOCK_MONOTONIC, so the lanes line up without
        translation. Writes to ``path`` when given; always returns the
        trace object."""
        from ...telemetry import (chrome_trace, request_trace_events,
                                  write_chrome_trace)
        from ...telemetry import core as _tcore
        rt = runtime if runtime is not None else _tcore.get_runtime()
        extra = request_trace_events(self.to_json())
        if path is None:
            return chrome_trace(rt, extra_events=extra)
        return write_chrome_trace(path, rt, extra_events=extra)
