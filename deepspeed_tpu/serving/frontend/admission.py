"""SLO-aware admission control ahead of the continuous-batching scheduler.

The scheduler's own backpressure (serving/scheduler.py) is a bounded
FIFO: it protects the engine, not the SLO. Under overload a FIFO admits
whatever arrived first, so a latency-tolerant bulk request can hold a
slot while an interactive request misses its deadline in the queue. This
controller sits between ``ServingFrontend.submit`` and
``ContinuousBatchScheduler.submit`` and makes the decisions a FIFO
cannot:

* **priority classes** — pending requests are held in a priority heap
  (lower value admits first, FIFO within a class), so under overload
  high-priority traffic admits ahead of earlier-arrived low-priority
  traffic;
* **deadline-feasibility shedding** — each request carries a token-cost
  estimate (weighted prompt-bucket prefill cost + ``max_new_tokens``);
  against the measured chunk throughput and the current token backlog,
  a request that would miss its deadline *even if admitted right now* is
  rejected immediately with a machine-readable reason instead of wasting
  a prefill and dying at a chunk boundary;
* **token-bucket rate limiting** — per-tenant buckets throttle an
  aggressive tenant at submission time so one caller cannot starve the
  pending queue.

Everything here is host-side Python with an injectable clock — no JAX,
unit-testable at CPU speed. Thread safety: ``offer`` / ``remove`` /
``pop`` serialize behind one internal lock (offers arrive on caller
threads, pops on the frontend's driver thread).
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...analysis import locks
from ...telemetry import core as telemetry

# machine-readable rejection reasons (the scheduler's REJECT_* constants
# cover its own queue_full / prompt_too_long / deadline_expired reasons)
REJECT_RATE_LIMITED = "rate_limited"
REJECT_FRONTEND_QUEUE_FULL = "frontend_queue_full"
REJECT_DEADLINE_INFEASIBLE = "deadline_infeasible"
REJECT_FRONTEND_CLOSED = "frontend_closed"
REJECT_MEMORY_INFEASIBLE = "memory_infeasible"

# priority classes: any int works (lower admits first); these names are
# the conventional three
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2

_seq_counter = itertools.count()


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity; ``try_acquire`` is all-or-nothing and never blocks (the
    frontend rejects instead of queueing throttled work)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate and burst must be > 0, "
                             f"got rate={rate} burst={burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def try_acquire(self, cost: float = 1.0) -> bool:
        now = self.clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False


class ChunkThroughputEstimator:
    """EWMA of decode throughput (tokens/s) observed per consumed chunk.
    ``rate()`` is None until the first observation — the controller never
    sheds on an unmeasured system (cold starts admit optimistically).

    Thread safety: ``record`` runs on a replica's driver thread while a
    fleet router reads ``rate``/``snapshot`` from caller threads, so the
    EWMA fold and the reads serialize behind one lock."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._lock = locks.make_lock("frontend.rate_estimator")
        self._rate: Optional[float] = None
        self.n_samples = 0

    def record(self, tokens: int, dt_s: float) -> None:
        if tokens <= 0 or dt_s <= 0:
            return
        sample = tokens / dt_s
        with self._lock:
            self._rate = sample if self._rate is None else (
                self.alpha * sample + (1.0 - self.alpha) * self._rate)
            self.n_samples += 1

    def rate(self) -> Optional[float]:
        with self._lock:
            return self._rate

    def seed(self, tokens_per_s: Optional[float]) -> bool:
        """Warm-start the EWMA from a peer's measurement (elastic
        scale-up: a fresh replica joins with the donor's rate instead of
        an unmeasured cold start, so drain-time scores don't flap).
        Only applies while unmeasured — real local samples always win.
        Returns True when the seed took."""
        if tokens_per_s is None or tokens_per_s <= 0:
            return False
        with self._lock:
            if self._rate is not None:
                return False
            self._rate = float(tokens_per_s)
            # n_samples stays 0: the snapshot still tells a router this
            # rate is inherited, not locally observed
            return True

    def snapshot(self) -> Dict[str, Any]:
        """One consistent read of the placement signal: EWMA tokens/s
        (None before the first chunk) and how many samples back it."""
        with self._lock:
            return {"tokens_per_s": self._rate,
                    "n_samples": self.n_samples}


@dataclasses.dataclass
class AdmissionConfig:
    """Knobs for the controller. ``rate_per_tenant`` is requests/s (None
    disables rate limiting); ``tenant_limits`` overrides (rate, burst)
    per tenant id. ``prefill_token_weight`` scales prompt tokens into
    decode-token-equivalents for the cost estimate — prefill processes
    its tokens in one batched program, so a prompt token costs a fraction
    of a decode token. ``feasibility_slack_s`` absorbs estimate noise
    before a deadline shed fires.

    ``shed_memory_infeasible`` adds the HBM-aware gate: a request whose
    prompt + token budget cannot fit one KV slot row (``slot_tokens``
    positions — wired from the engine arena's ``max_seq_len`` by the
    frontend when left None) is rejected at offer time with
    ``memory_infeasible`` instead of being admitted and silently
    truncated at the arena edge. OFF by default — truncation is the
    historical behavior.

    ``fused_prefill_chunk`` switches the cost model for fused
    chunked-prefill engines (wired from ``engine.prefill_chunk`` by the
    frontend): prompt tokens no longer ride a separate bucketed prefill
    program whose relative cost ``prefill_token_weight`` approximates —
    they flow through the SAME decode scan, one C-token chunk per scan
    step, so a prompt's decode-token-equivalent cost is exactly
    ``ceil(prompt_len / C)`` scan steps. None keeps the bucket-weight
    estimate."""
    max_pending: int = 256
    prefill_token_weight: float = 0.15
    feasibility_slack_s: float = 0.0
    rate_per_tenant: Optional[float] = None
    burst_per_tenant: float = 8.0
    tenant_limits: Dict[str, Tuple[float, float]] = \
        dataclasses.field(default_factory=dict)
    shed_memory_infeasible: bool = False
    slot_tokens: Optional[int] = None
    fused_prefill_chunk: Optional[int] = None
    # tiered KV (serving/kv_tiers.py): the tier NEVER raises the
    # per-ticket wall — active-sequence KV cannot live below HBM, so a
    # request past ``slot_tokens`` is infeasible tier or no tier. What
    # the tier buys is AGGREGATE headroom: cold prefixes demote instead
    # of occupying the pool, so the pending queue's total KV demand may
    # exceed the HBM pool (``pool_tokens``) by the tier capacity
    # (``tier_tokens``) at a discount (promotion costs a round trip).
    # Offers past that ladder-wide ceiling shed with
    # ``memory_infeasible`` backpressure. All three wired from the
    # engine by the frontend when left None; tier_tokens 0/None keeps
    # the pure per-ticket HBM gate (historical queueing behavior).
    pool_tokens: Optional[int] = None
    tier_tokens: Optional[int] = None
    tier_discount: float = 0.5

    def cost_tokens(self, ticket: "Ticket") -> float:
        """Decode-token-equivalent cost of serving ``ticket`` under the
        active cost model: scan steps (``ceil(prompt_len / chunk) +
        max_new_tokens``) when the engine inlines prefill chunks into
        the decode scan, weighted prompt tokens otherwise."""
        if self.fused_prefill_chunk:
            chunks = -(-ticket.prompt_len // self.fused_prefill_chunk)
            return float(chunks + ticket.max_new_tokens)
        return ticket.cost_tokens(self.prefill_token_weight)


@dataclasses.dataclass
class Ticket:
    """One pending admission decision. ``payload`` is opaque to the
    controller (the frontend stores its StreamHandle there)."""
    prompt_len: int
    max_new_tokens: int
    priority: int = PRIORITY_NORMAL
    tenant: str = "default"
    deadline_s: Optional[float] = None       # absolute clock time
    slo_ttft_s: Optional[float] = None       # target, tracked not enforced
    payload: Any = None
    trace_id: Optional[str] = None           # distributed journey id
    seq: int = dataclasses.field(default_factory=lambda: next(_seq_counter))
    cancelled: bool = False                  # tombstone (lazy heap removal)

    def cost_tokens(self, prefill_weight: float) -> float:
        """Estimated decode-token-equivalent cost of serving this
        request to completion."""
        return self.prompt_len * prefill_weight + self.max_new_tokens


class AdmissionController:
    """Priority-ordered, SLO-aware admission queue.

    Flow: callers ``offer`` tickets (rate limit + pending bound + dead
    deadline checked immediately → reason or enqueued); the driver
    ``pop``s up to ``room`` tickets per iteration in (priority, seq)
    order, shedding any whose deadline has become infeasible against the
    measured throughput; ``remove`` tombstones a ticket a caller
    cancelled while it was still pending."""

    def __init__(self, config: Optional[AdmissionConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or AdmissionConfig()
        self.clock = clock
        self._lock = locks.make_lock("frontend.admission")
        self._heap: List[Tuple[int, int, Ticket]] = []
        self._pending = 0                    # live (non-tombstone) tickets
        self._pending_kv_tokens = 0          # their summed KV demand
        self._buckets: Dict[str, TokenBucket] = {}
        self.n_offered = 0
        self.n_rate_limited = 0
        self.n_shed = 0
        self.n_memory_infeasible = 0

    # ------------------------------------------------------------ offers
    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        cfg = self.config
        limits = cfg.tenant_limits.get(tenant)
        if limits is None and cfg.rate_per_tenant is None:
            return None
        bucket = self._buckets.get(tenant)
        if bucket is None:
            rate, burst = limits if limits is not None else (
                cfg.rate_per_tenant, cfg.burst_per_tenant)
            bucket = self._buckets[tenant] = TokenBucket(
                rate, burst, clock=self.clock)
        return bucket

    def offer(self, ticket: Ticket) -> Optional[str]:
        """Admit ``ticket`` into the pending queue, or return a rejection
        reason. The rate-limit token is consumed only on acceptance
        paths (a bound-rejected request does not burn tenant budget)."""
        reason = self._offer_locked(ticket)
        if reason is not None:
            telemetry.count(f"frontend/reject/{reason}", 1.0)
        return reason

    def _offer_locked(self, ticket: Ticket) -> Optional[str]:
        cfg = self.config
        with self._lock:
            self.n_offered += 1
            if ticket.deadline_s is not None and \
                    self.clock() >= ticket.deadline_s:
                from ..scheduler import REJECT_DEADLINE_EXPIRED
                return REJECT_DEADLINE_EXPIRED
            demand = ticket.prompt_len + ticket.max_new_tokens
            if cfg.shed_memory_infeasible and cfg.slot_tokens:
                # per-ticket wall is pure HBM: active-sequence KV can
                # never demote, so a request past one slot row / the
                # pool can NEVER be served — tier or no tier
                if demand > cfg.slot_tokens:
                    self.n_memory_infeasible += 1
                    return REJECT_MEMORY_INFEASIBLE
            if cfg.shed_memory_infeasible and cfg.tier_tokens \
                    and cfg.pool_tokens:
                # tier-aware AGGREGATE gate: the pending queue's total
                # KV demand may exceed the HBM pool by the lower tiers'
                # capacity at a discount (promotion costs a round
                # trip); past that the ladder itself would thrash, so
                # shed instead of queueing forever
                cap = float(cfg.pool_tokens) \
                    + cfg.tier_discount * float(cfg.tier_tokens)
                if self._pending_kv_tokens + demand > cap:
                    self.n_memory_infeasible += 1
                    return REJECT_MEMORY_INFEASIBLE
            if self._pending >= cfg.max_pending:
                return REJECT_FRONTEND_QUEUE_FULL
            bucket = self._bucket_for(ticket.tenant)
            if bucket is not None and not bucket.try_acquire():
                self.n_rate_limited += 1
                return REJECT_RATE_LIMITED
            heapq.heappush(self._heap,
                           (ticket.priority, ticket.seq, ticket))
            self._pending += 1
            self._pending_kv_tokens += demand
            return None

    def remove(self, ticket: Ticket) -> bool:
        """Tombstone a still-pending ticket (cancellation before it ever
        reached the scheduler). Returns False if it already left the
        queue."""
        with self._lock:
            if ticket.cancelled:
                return False
            for _, _, t in self._heap:
                if t is ticket:
                    ticket.cancelled = True
                    self._pending -= 1
                    self._pending_kv_tokens -= \
                        ticket.prompt_len + ticket.max_new_tokens
                    return True
            return False

    # -------------------------------------------------------------- pops
    def pop(self, *, room: int, rate: Optional[float],
            backlog_tokens: float
            ) -> Tuple[List[Ticket], List[Tuple[Ticket, str]]]:
        """Pop up to ``room`` admissible tickets in priority order.
        ``rate`` is the measured decode throughput (tokens/s, None before
        any measurement); ``backlog_tokens`` is the token-equivalent work
        already admitted ahead of these tickets (running remainders +
        scheduler queue). Returns (admits, [(shed, reason), ...]) — a
        shed ticket would miss its deadline even if admitted now, so it
        is rejected early rather than served late."""
        from ..scheduler import REJECT_DEADLINE_EXPIRED
        cfg = self.config
        admits: List[Ticket] = []
        sheds: List[Tuple[Ticket, str]] = []
        now = self.clock()
        with self._lock:
            while self._heap and len(admits) < room:
                _, _, ticket = heapq.heappop(self._heap)
                if ticket.cancelled:
                    continue
                self._pending -= 1
                self._pending_kv_tokens -= \
                    ticket.prompt_len + ticket.max_new_tokens
                if ticket.deadline_s is not None and \
                        now >= ticket.deadline_s:
                    self.n_shed += 1
                    sheds.append((ticket, REJECT_DEADLINE_EXPIRED))
                    continue
                if ticket.deadline_s is not None and rate:
                    cost = cfg.cost_tokens(ticket)
                    eta = now + (backlog_tokens + cost) / rate
                    if eta > ticket.deadline_s + cfg.feasibility_slack_s:
                        self.n_shed += 1
                        sheds.append((ticket, REJECT_DEADLINE_INFEASIBLE))
                        continue
                admits.append(ticket)
                backlog_tokens += cfg.cost_tokens(ticket)
            pending = self._pending
        for _, reason in sheds:
            telemetry.count(f"frontend/shed/{reason}", 1.0)
        telemetry.gauge("frontend/pending", float(pending))
        return admits, sheds

    # ----------------------------------------------------------- queries
    @property
    def pending(self) -> int:
        with self._lock:
            return self._pending

    def snapshot(self) -> Dict[str, Any]:
        """One locked, allocation-cheap read of every placement signal a
        fleet router needs: pending depth + bound, decision counters, and
        per-tenant rate-limit state (current bucket tokens / rate /
        burst). No heap walk beyond the bucket dict — O(tenants).
        Copy-out only under the lock (scalars + one bounded dict, no
        JSON rendering): lockcheck-audited snapshot discipline."""
        with self._lock:
            return {
                "pending": self._pending,
                "max_pending": self.config.max_pending,
                "n_offered": self.n_offered,
                "n_rate_limited": self.n_rate_limited,
                "n_shed": self.n_shed,
                "n_memory_infeasible": self.n_memory_infeasible,
                "rate_limits": {
                    tenant: {"tokens": b._tokens, "rate": b.rate,
                             "burst": b.burst}
                    for tenant, b in self._buckets.items()},
            }

    def tickets(self) -> List[Ticket]:
        """Locked copy of the live pending tickets (no pops, no
        tombstones): the frontend's ``request_snapshot`` accessor uses
        it to find handles that haven't reached the engine yet."""
        with self._lock:
            return [t for _, _, t in self._heap if not t.cancelled]

    def drain(self) -> List[Ticket]:
        """Remove and return every live pending ticket (crash/teardown:
        the frontend resolves their handles with a terminal status, or a
        router re-homes them — graceful drain / crash re-route)."""
        with self._lock:
            out = [t for _, _, t in self._heap if not t.cancelled]
            self._heap = []
            self._pending = 0
            self._pending_kv_tokens = 0
            return out
