"""Fleet-health probes: backend watchdog + readiness state machine.

Motivated by ROADMAP Open item 5: bench rounds 4-5 burned their entire
budget dispatching to a dead TPU backend because *nothing in-process
could answer "is the accelerator alive right now?"*. This module makes
that a first-class probe:

* :class:`BackendWatchdog` — a periodic heartbeat that dispatches one
  tiny jitted op and syncs it with a hard timeout. The sync runs in a
  short-lived worker thread so a wedged runtime (the observed failure
  mode: dispatch blocks forever inside XLA) marks the backend dead
  instead of wedging the watchdog too; while a worker is still hung, no
  new one is spawned (no thread pileup on a dead backend). Recovery is
  automatic — the next heartbeat that completes flips it back.
* :class:`HealthMonitor` — composes the checks ``/readyz`` answers
  from: frontend driver-thread liveness + crash flag, watchdog state,
  admission-queue saturation, plus arbitrary injected callables. Pure
  host-side logic with injectable fakes — the state machine is fully
  unit-testable without a backend.

Wired to HTTP by :class:`~deepspeed_tpu.telemetry.exposition
.MetricsServer`. JAX is imported lazily, only inside the default
heartbeat — constructing monitors/watchdogs with injected probes stays
stdlib-only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...analysis import locks
from ...telemetry import core as telemetry

_HEARTBEAT_FN = None


def default_heartbeat() -> None:
    """Dispatch one tiny jitted op and block until the device answers.
    The program is cached after the first call, so a steady-state beat
    measures dispatch + execute + transfer, not compilation."""
    global _HEARTBEAT_FN
    import jax
    import jax.numpy as jnp
    import numpy as np
    if _HEARTBEAT_FN is None:
        _HEARTBEAT_FN = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
    out = _HEARTBEAT_FN(jnp.arange(8, dtype=jnp.float32))
    np.asarray(out)          # the sync: a dead backend hangs right here


class BackendWatchdog:
    """Periodic accelerator heartbeat with a hard timeout.

    ``beat()`` runs one probe synchronously (the unit-test entry point);
    ``start()`` runs it every ``interval_s`` on a daemon thread. A probe
    that raises OR takes longer than ``timeout_s`` counts as a failure;
    ``ok`` goes False after ``max_failures`` consecutive failures and
    True again on the next success."""

    def __init__(self, *, interval_s: float = 5.0, timeout_s: float = 10.0,
                 heartbeat_fn: Optional[Callable[[], Any]] = None,
                 max_failures: int = 1,
                 flight_recorder=None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.heartbeat_fn = heartbeat_fn or default_heartbeat
        self.max_failures = max(1, int(max_failures))
        # optional telemetry.flight_recorder.FlightRecorder: dumps a
        # postmortem once per healthy->unhealthy flip (and records every
        # heartbeat failure); its dumps then include watchdog history
        self.flight_recorder = flight_recorder
        if flight_recorder is not None \
                and getattr(flight_recorder, "watchdog", None) is None:
            flight_recorder.watchdog = self
        self.clock = clock
        self._lock = locks.make_lock("frontend.health")
        self._ok = True                  # optimistic until a probe fails
        self._consecutive_failures = 0
        self.n_beats = 0
        self.n_failures = 0
        self.last_beat_s: Optional[float] = None   # last probe latency
        self.last_ok_t: Optional[float] = None
        self.last_error: Optional[str] = None
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ probing
    def beat(self) -> bool:
        """One heartbeat, synchronously (bounded by ``timeout_s``).
        Returns the post-probe ``ok`` state."""
        with self._lock:
            worker = self._worker
        if worker is not None and worker.is_alive():
            # a previous probe is still hung inside the runtime: that IS
            # the failure signal; spawning more threads at a dead
            # backend only piles them up
            self._record(False, None, "previous heartbeat still hung")
            return self.ok
        result: Dict[str, Any] = {}

        def probe():
            try:
                self.heartbeat_fn()
                result["ok"] = True
            except BaseException as e:  # noqa: BLE001 — recorded, not raised
                result["error"] = f"{type(e).__name__}: {e}"

        t0 = self.clock()
        worker = threading.Thread(target=probe, name="backend-heartbeat",
                                  daemon=True)
        with self._lock:
            self._worker = worker
        worker.start()
        worker.join(self.timeout_s)
        took = self.clock() - t0
        if worker.is_alive():
            self._record(False, took,
                         f"heartbeat exceeded {self.timeout_s}s")
        elif result.get("ok"):
            self._record(True, took, None)
        else:
            self._record(False, took,
                         result.get("error", "heartbeat failed"))
        return self.ok

    def _record(self, ok: bool, took: Optional[float],
                error: Optional[str]) -> None:
        flipped_unhealthy = False
        with self._lock:
            self.n_beats += 1
            self.last_beat_s = took
            if ok:
                self._consecutive_failures = 0
                self._ok = True
                self.last_ok_t = self.clock()
                self.last_error = None
            else:
                self.n_failures += 1
                self._consecutive_failures += 1
                self.last_error = error
                if self._consecutive_failures >= self.max_failures:
                    flipped_unhealthy = self._ok
                    self._ok = False
            consecutive = self._consecutive_failures
        fr = self.flight_recorder
        if fr is not None and not ok:
            fr.record("watchdog_failure", error=error, took_s=took,
                      consecutive=consecutive)
            if flipped_unhealthy:
                # once per healthy->unhealthy transition, not per beat
                try:
                    fr.dump(reason="watchdog_max_failures", error=error)
                except Exception:  # noqa: BLE001 — probes never raise
                    pass
        telemetry.gauge("health/backend_ok", 1.0 if self.ok else 0.0)
        if took is not None:
            telemetry.gauge("health/heartbeat_s", float(took))

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "BackendWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="backend-watchdog", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 1.0)
            self._thread = None

    # ------------------------------------------------------------- queries
    @property
    def ok(self) -> bool:
        with self._lock:
            return self._ok

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ok": self._ok,
                "n_beats": self.n_beats,
                "n_failures": self.n_failures,
                "consecutive_failures": self._consecutive_failures,
                "last_beat_s": self.last_beat_s,
                "last_error": self.last_error,
                "timeout_s": self.timeout_s,
            }


class HealthMonitor:
    """The readiness state machine behind ``/readyz``.

    ``check()`` -> ``(ready, reasons, details)``: ready iff every wired
    check passes. Checks (all optional — wire what the process has):

    * ``frontend`` — its driver thread must be alive and not crashed
      (``driver_dead`` / ``driver_crashed``), not ``draining`` (set by
      ``FleetRouter.retire_replica``: the replica is finishing its
      in-engine work and must receive nothing new, so external
      balancers mirror the router's placement exclusion), and its
      pending admission queue below ``queue_saturation`` of
      ``max_pending`` (``admission_saturated``: shedding load is
      degraded, not dead — but a fleet router should stop placing
      traffic here);
    * ``watchdog`` — ``backend_unresponsive`` when the heartbeat says
      the accelerator is gone;
    * ``slo`` + ``slo_fast_burn_threshold`` — opt-in (both must be set):
      ``slo_fast_burn`` when the :class:`~deepspeed_tpu.telemetry.slo
      .SLOEngine`'s fastest-window burn rate exceeds the threshold.
      Burning the error budget that fast means the replica is degraded
      even if every liveness probe still answers;
    * ``anomaly`` — opt-in: an :class:`~deepspeed_tpu.telemetry.anomaly
      .AnomalyDetector` whose tripped state degrades the replica
      (reason ``anomaly``, the tripped metrics in the details) until
      the detector re-arms;
    * ``checks`` — extra ``name -> callable() -> bool`` probes.
    """

    def __init__(self, *, frontend=None, watchdog: Optional[
                     BackendWatchdog] = None,
                 checks: Optional[Dict[str, Callable[[], bool]]] = None,
                 queue_saturation: float = 0.95,
                 slo=None,
                 slo_fast_burn_threshold: Optional[float] = None,
                 anomaly=None):
        self.frontend = frontend
        self.watchdog = watchdog
        self.checks = dict(checks or {})
        self.queue_saturation = float(queue_saturation)
        self.slo = slo
        self.slo_fast_burn_threshold = (
            None if slo_fast_burn_threshold is None
            else float(slo_fast_burn_threshold))
        self.anomaly = anomaly

    def check(self) -> Tuple[bool, List[str], Dict[str, Any]]:
        reasons: List[str] = []
        details: Dict[str, Any] = {}
        fe = self.frontend
        if fe is not None:
            alive = fe.driver_alive
            details["driver_alive"] = alive
            if fe.crashed:
                reasons.append("driver_crashed")
                details["crash_error"] = str(fe.crash_error)
            elif not alive:
                reasons.append("driver_dead")
            if getattr(fe, "draining", False):
                reasons.append("draining")
                details["draining"] = True
            pending = fe.pending_admission
            cap = fe.max_pending
            details["pending_admission"] = pending
            details["max_pending"] = cap
            if cap and pending >= self.queue_saturation * cap:
                reasons.append("admission_saturated")
        wd = self.watchdog
        if wd is not None:
            st = wd.state()
            details["watchdog"] = st
            if not st["ok"]:
                reasons.append("backend_unresponsive")
        if self.slo is not None and self.slo_fast_burn_threshold is not None:
            try:
                fast = float(self.slo.fast_burn_rate())
            except Exception as e:  # noqa: BLE001 — a probe never raises
                fast = 0.0
                details["slo_error"] = f"{type(e).__name__}: {e}"
            details["slo_fast_burn_rate"] = fast
            details["slo_fast_burn_threshold"] = self.slo_fast_burn_threshold
            if fast > self.slo_fast_burn_threshold:
                reasons.append("slo_fast_burn")
        if self.anomaly is not None:
            try:
                tripped = bool(self.anomaly.tripped)
                details["anomaly"] = self.anomaly.trip_reasons()
            except Exception as e:  # noqa: BLE001 — a probe never raises
                tripped = False
                details["anomaly_error"] = f"{type(e).__name__}: {e}"
            if tripped:
                reasons.append("anomaly")
        for name, probe in self.checks.items():
            try:
                ok = bool(probe())
            except Exception as e:
                ok = False
                details[f"{name}_error"] = f"{type(e).__name__}: {e}"
            details[name] = ok
            if not ok:
                reasons.append(name)
        ready = not reasons
        telemetry.gauge("health/ready", 1.0 if ready else 0.0)
        return ready, reasons, details
