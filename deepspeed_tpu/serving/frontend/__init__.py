"""Serving frontend: streaming handles, SLO-aware admission, tracing.

Layers on top of the continuous-batching core (``serving.engine``):
``ServingFrontend`` owns a background engine-driver thread and exposes a
thread-safe ``submit -> StreamHandle`` API with priority/deadline-aware
admission (``admission.py``) and per-request span tracing
(``tracing.py``). See docs/serving.md ("Frontend").
"""

from .admission import (AdmissionConfig, AdmissionController,  # noqa: F401
                        ChunkThroughputEstimator, PRIORITY_HIGH,
                        PRIORITY_LOW, PRIORITY_NORMAL,
                        REJECT_DEADLINE_INFEASIBLE, REJECT_FRONTEND_CLOSED,
                        REJECT_FRONTEND_QUEUE_FULL, REJECT_MEMORY_INFEASIBLE,
                        REJECT_RATE_LIMITED, Ticket, TokenBucket)
from .tracing import EVENTS, RequestTrace, TraceLog  # noqa: F401
from .frontend import (ServingFrontend, StreamHandle,  # noqa: F401
                       TERMINAL_STATUSES)
from .health import BackendWatchdog, HealthMonitor  # noqa: F401
