"""FleetRouter: one submit() surface over N data-parallel serving
replicas.

One continuous-batching :class:`~deepspeed_tpu.serving.engine
.ServingEngine` saturates one mesh slice; a serving deployment runs
several — data-parallel replicas with identical weights — behind one
frontend. This module is that frontend-of-frontends. Each replica is a
``ServingEngine`` owned by its own :class:`ServingFrontend` (its own
daemon driver thread, admission controller, throughput estimator), and
the router only ever makes PLACEMENT decisions; after placement the
request's whole lifecycle — admission, prefill, decode chunks, token
streaming — is the chosen replica's, and the caller holds a perfectly
ordinary :class:`StreamHandle`.

Placement, in order:

1. **Health**: replicas whose driver thread has crashed (or that the
   router already marked dead) never receive traffic — the
   ``HealthMonitor`` contract ("a fleet router should stop placing
   traffic here") enforced at the router.
2. **Prefix affinity**: hash the prompt (``PrefixCache.key_for`` — the
   exact token-byte key the paged allocator uses) and prefer replicas
   whose :class:`PrefixCache` already holds it: a hit replica serves
   the prompt's prefill almost for free by block sharing, so sending
   the request anywhere else throws away cached device work. The probe
   is a pure peek (no LRU refresh, no counters).
3. **Least loaded**: among the remaining candidates, pick the lowest
   estimated drain time — outstanding work from the frontend's locked
   ``load_snapshot()`` (admission-pending + engine backlog tokens)
   over the replica's EWMA decode throughput.

**Dead-replica drain + in-flight replay**: each frontend gets the
router as its ``on_crash`` hook. When a driver crashes, EVERY live
handle is re-homed on surviving replicas via
``ServingFrontend.adopt`` — the SAME handle objects keep streaming to
their callers. Work that never touched the device restarts from
scratch; requests that already prefilled/streamed are REPLAYED (the
survivor re-prefills prompt + emitted tokens — a paged ``PrefixCache``
hit when a twin stream replayed first — and emitted-token dedup keeps
the stream seamless). The crashed replica is marked dead and drops out
of placement.

**Elastic fleet**: the replica set is no longer fixed at construction.
``add_replica()`` grows the fleet (from a ``replica_factory`` —
checkpoint-backed engines share committed params — with the EWMA
warm-started from a peer), ``retire_replica()`` shrinks it gracefully:
the replica enters a ``draining`` placement state (excluded from
routing, still ``alive``), its admission tail is adopted by survivors,
in-engine chunks retire naturally, and ``poll_draining()`` finalizes
the retirement once idle. :class:`~.elastic.ElasticController` turns
this crank from SLO burn rates and drain-time estimates.

Telemetry: every replica's driver thread is labeled (``replica=<id>``
via ``telemetry.replica_label``) so per-replica gauges/counters stay
distinguishable in one process-wide runtime; the router's own counters
(``fleet/routed``, ``fleet/affinity_hits``, ``fleet/rerouted``,
``fleet/replayed``, ``fleet/reroute_failed``,
``fleet/replica_crashes``, ``fleet/scale_up``, ``fleet/scale_down``,
``fleet/drained``, ``fleet/migrated``, ``fleet/migrate_bytes``,
``fleet/migrate_failed``) are recorded unlabeled — they are
fleet-level, not per-replica.

**Cross-host fleet**: ``add_remote()`` joins a replica that lives on
the far side of the ``dstpu-fleet-v1`` wire (:mod:`.transport` /
:mod:`.remote`) — the in-process frontend is just the loopback case of
the same surface. ``migrate()`` is the live KV-block migration verb:
a running request's blocks + cursor move to a less-loaded replica
mid-decode (``rebalance()`` turns this crank under skew), with the
caller's handle streaming across the hop with zero lost or duplicated
tokens.

Host-side only — this module never imports JAX.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ...analysis import locks
from ...telemetry import core as telemetry
from ...telemetry.journey import journey_trace_events, new_trace_id
from ...utils.logging import logger
from ..engine import MigrationError
from ..frontend.admission import AdmissionConfig, PRIORITY_NORMAL
from ..frontend.frontend import ServingFrontend, StreamHandle
from ..paged_kv import PrefixCache


@dataclasses.dataclass
class FleetReplica:
    """One replica's slot in the fleet: engine + owning frontend +
    router-side health/lifecycle marks.

    ``draining`` is the graceful-retirement state: the replica is still
    ``alive`` (its driver keeps pumping so in-engine chunks retire
    naturally) but no longer ``routable`` — placement skips it. Once
    idle, ``FleetRouter.poll_draining`` closes the frontend and flips
    ``retired``."""
    rid: int
    engine: Any
    frontend: ServingFrontend
    dead: bool = False
    draining: bool = False
    retired: bool = False

    @property
    def alive(self) -> bool:
        return (not self.dead and not self.retired
                and self.frontend.driver_alive)

    @property
    def routable(self) -> bool:
        """Eligible for NEW placements: alive and not draining."""
        return self.alive and not self.draining


class FleetRouter:
    """Route requests across N ``ServingEngine`` replicas.

    ``engines`` are pre-built replicas (identical weights — the router
    assumes any replica can serve any request). Each is wrapped in a
    ``ServingFrontend`` with its own driver thread; the router owns
    those frontends and ``close()`` drains all of them. ``admission``
    is copied per replica (the frontend mutates its config in place to
    size memory-aware shedding from the engine arena). ``remotes`` are
    :class:`~.remote.RemoteReplica` clients joining at construction —
    a fleet may be entirely remote (``engines=[]``).
    """

    def __init__(self, engines: Sequence[Any], *,
                 remotes: Optional[Sequence[Any]] = None,
                 admission: Optional[AdmissionConfig] = None,
                 affinity: bool = True,
                 feed_depth: Optional[int] = None,
                 idle_wait_s: float = 0.005,
                 replica_factory=None,
                 clock=time.monotonic):
        if not engines and not remotes:
            raise ValueError("FleetRouter needs at least one engine "
                             "or remote replica")
        self._clock = clock
        self.affinity = bool(affinity)
        self._lock = locks.make_lock("fleet.router")
        # per-replica frontend construction knobs, kept so add_replica()
        # builds elastically grown replicas exactly like the originals
        self._admission = admission
        self._feed_depth = feed_depth
        self._idle_wait_s = idle_wait_s
        # ``replica_factory()`` -> a fresh engine with committed params
        # (checkpoint-backed warm start): the elastic controller's
        # growth path when ``add_replica`` isn't handed an engine
        self.replica_factory = replica_factory
        self.n_routed = 0
        self.n_affinity_hits = 0
        self.n_tier_fetches = 0
        self.n_rerouted = 0
        self.n_replayed = 0
        self.n_reroute_failed = 0
        self.n_replica_crashes = 0
        self.n_scale_up = 0
        self.n_scale_down = 0
        self.n_drained = 0
        self.n_migrated = 0
        self.n_migrate_failed = 0
        self.migrate_bytes = 0
        # journey journal: placement / reroute / crash / migration
        # records under one trace id per request — the input to
        # ``export_chrome``'s journey lanes and the in-flight replay
        # loop (bounded: a long-running router never grows without
        # bound)
        self._placements: deque = deque(maxlen=4096)
        self._reroutes: deque = deque(maxlen=1024)
        self._crashes: deque = deque(maxlen=256)
        self._migrations: deque = deque(maxlen=1024)
        self.replicas: List[FleetReplica] = []
        self._by_frontend: Dict[int, FleetReplica] = {}
        self._next_rid = 0
        for eng in engines:
            self._spawn_replica(eng)
        # construction-time remote replicas join without the scale-up
        # counters — they are the fleet's initial size, not growth
        for rem in (remotes or ()):
            self._join_remote(rem)

    def _spawn_replica(self, engine: Any) -> FleetReplica:
        """Wrap one engine in a frontend + FleetReplica and register it
        (construction path and ``add_replica`` share it)."""
        rid = self._next_rid
        self._next_rid += 1
        cfg = dataclasses.replace(self._admission) \
            if self._admission is not None else None
        fe = ServingFrontend(engine, admission=cfg,
                             feed_depth=self._feed_depth,
                             idle_wait_s=self._idle_wait_s,
                             on_crash=self._on_replica_crash,
                             telemetry_label=str(rid),
                             clock=self._clock)
        rep = FleetReplica(rid=rid, engine=engine, frontend=fe)
        self.replicas.append(rep)
        self._by_frontend[id(fe)] = rep
        return rep

    # ------------------------------------------------------- public API
    def submit(self, prompt: Union[Sequence[int], np.ndarray], *,
               priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> StreamHandle:
        """Place one request and enqueue it; returns the chosen
        replica's StreamHandle immediately. With every replica dead the
        handle resolves ``rejected`` (``frontend_closed``) — same
        no-exception contract as ``ServingFrontend.submit``.

        Every submit mints a ``trace_id`` that rides the handle, the
        admission ticket, the engine request, and the chosen replica's
        trace segment; the placement decision (candidate scores,
        affinity hit, chosen replica) is journaled under that id."""
        trace_id = new_trace_id()
        t0 = self._clock()
        replica, decision = self._place_decision(prompt)
        t1 = self._clock()
        telemetry.count("fleet/routed")
        with self._lock:
            self.n_routed += 1
        handle = replica.frontend.submit(
            prompt, priority=priority, tenant=tenant,
            slo_ttft_s=slo_ttft_s, deadline_s=deadline_s,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            trace_id=trace_id)
        telemetry.instant("fleet/placement", trace_id=trace_id,
                          replica=replica.rid,
                          affinity_hit=decision["affinity_hit"])
        with self._lock:
            self._placements.append({
                "trace_id": trace_id, "uid": handle.uid, "t": t0,
                "dur_s": t1 - t0, "replica": replica.rid,
                "affinity_hit": decision["affinity_hit"],
                "scores": decision["scores"],
                "candidates": decision["candidates"]})
        return handle

    def close(self, timeout: Optional[float] = None) -> None:
        for rep in self.replicas:
            rep.frontend.close(timeout)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- placement
    def _place(self, prompt) -> FleetReplica:
        return self._place_decision(prompt)[0]

    def _place_decision(self, prompt) -> Tuple[FleetReplica,
                                               Dict[str, Any]]:
        """Choose a replica AND return the decision record (candidate
        rids, per-candidate load scores, affinity hit) that the journey
        journal attaches to the request's ``route`` span."""
        decision: Dict[str, Any] = {"affinity_hit": False, "scores": {},
                                    "candidates": []}
        candidates = [r for r in self.replicas if r.routable]
        if not candidates:
            # no routable replica: fall back to any alive-but-draining
            # one (serving late beats rejecting), else any frontend will
            # reject-with-reason (frontend_closed) — deliberate, so
            # callers get a terminal handle instead of an exception
            candidates = [r for r in self.replicas if r.alive]
        if not candidates:
            return self.replicas[0], decision
        if self.affinity:
            key = PrefixCache.key_for(prompt)
            # probe even a sole candidate: placement has no choice, but
            # a hit must still short-circuit the tier-fetch fallback —
            # otherwise a replica already holding the prefix in HBM
            # gets a redundant cross-replica bundle pulled at it
            hits = [r for r in candidates if self._holds_prefix(r, key)]
            if hits:
                telemetry.count("fleet/affinity_hits")
                with self._lock:
                    self.n_affinity_hits += 1
                candidates = hits
                decision["affinity_hit"] = True
            else:
                # tier-fetch fallback: no PLACEABLE replica holds the
                # prefix, but an unroutable one (draining, or load-
                # filtered out) may still hold it in its DRAM/NVMe tier
                # — the chosen replica pulls the bundle over
                # ``/v1/prefix?fetch=1`` before the submit, so the
                # request admits warm instead of re-prefilling
                cand_rids = {r.rid for r in candidates}
                holder = next(
                    (r for r in self.replicas
                     if r.alive and r.rid not in cand_rids
                     and self._holds_prefix(r, key)), None)
                if holder is not None:
                    decision["candidates"] = [r.rid for r in candidates]
                    if len(candidates) > 1:
                        scores = {r.rid: self._load_score(r)
                                  for r in candidates}
                        decision["scores"] = scores
                        target = min(candidates,
                                     key=lambda r: scores[r.rid])
                    else:
                        target = candidates[0]
                    if self._tier_fetch(holder, target, key):
                        decision["tier_fetch"] = holder.rid
                        telemetry.count("fleet/tier_fetches")
                        with self._lock:
                            self.n_tier_fetches += 1
                    return target, decision
        decision["candidates"] = [r.rid for r in candidates]
        if len(candidates) == 1:
            return candidates[0], decision
        scores = {r.rid: self._load_score(r) for r in candidates}
        decision["scores"] = scores
        return min(candidates, key=lambda r: scores[r.rid]), decision

    @staticmethod
    def _tier_fetch(holder: FleetReplica, target: FleetReplica,
                    key: bytes) -> bool:
        """Pull ``key``'s demoted prefix from ``holder`` and install it
        into ``target``'s DRAM tier. Best-effort: any failure just means
        the request prefills normally on ``target``."""
        try:
            fetch = getattr(holder.frontend, "fetch_prefix", None)
            install = getattr(target.frontend, "install_prefix", None)
            if fetch is None or install is None:
                return False
            bundle = fetch(key)
            if bundle is None:
                return False
            return bool(install(bundle))
        except Exception:  # noqa: BLE001 — fetch is an optimization
            return False

    @staticmethod
    def _holds_prefix(replica: FleetReplica, key: bytes) -> bool:
        # prefer the frontend's probe (in-process: a pure engine peek;
        # remote: ``GET /v1/prefix`` — the transport made affinity a
        # frontend surface, so the router stops reaching into engines)
        probe = getattr(replica.frontend, "holds_prefix", None)
        if probe is not None:
            try:
                return bool(probe(key))
            except Exception:  # noqa: BLE001 — affinity is best-effort
                return False
        kv = getattr(replica.engine, "kv", None)
        if kv is None or not getattr(kv, "prefix_enabled", False):
            return False
        return key in kv.prefix_cache

    @staticmethod
    def _load_score(replica: FleetReplica) -> float:
        """Estimated drain time: outstanding tokens over EWMA decode
        throughput. Admission-pending requests haven't sized their
        decode yet, so they count by the engine-side backlog convention
        (prompt + budget) folded into ``pending`` as request counts —
        with homogeneous data-parallel replicas the ordering is what
        matters, not the absolute seconds."""
        snap = replica.frontend.load_snapshot()
        outstanding = (float(snap["engine_backlog_tokens"])
                       + float(snap["admission"]["pending"]))
        rate = snap["throughput"]["tokens_per_s"]
        return outstanding / rate if rate else outstanding

    # --------------------------------------------------------- elasticity
    def add_replica(self, engine: Any = None, *,
                    warm_start: bool = True) -> FleetReplica:
        """Grow the fleet by one replica. ``engine`` defaults to a fresh
        one from ``replica_factory`` (checkpoint-backed: the factory
        builds it from the same committed params the fleet serves, so
        it joins ready — no weight transfer on the serving path). With
        ``warm_start`` the new replica's throughput EWMA is seeded from
        the fastest measured peer's ``load_snapshot()``, so the
        autoscaler's drain-time scores don't flap while the newcomer is
        still unmeasured."""
        if engine is None:
            if self.replica_factory is None:
                raise ValueError(
                    "add_replica() needs an engine or a replica_factory")
            engine = self.replica_factory()
        donor_rate: Optional[float] = None
        if warm_start:
            rates = [r.frontend.load_snapshot()["throughput"]
                     ["tokens_per_s"] for r in self.replicas if r.alive]
            rates = [float(x) for x in rates if x]
            if rates:
                donor_rate = max(rates)
        rep = self._spawn_replica(engine)
        if donor_rate is not None:
            rep.frontend._estimator.seed(donor_rate)
        with self._lock:
            self.n_scale_up += 1
        telemetry.count("fleet/scale_up")
        telemetry.gauge("fleet/size", float(self.n_routable))
        logger.info(f"fleet scale-up: replica {rep.rid} joined "
                    f"(ewma seed={donor_rate})")
        return rep

    def _join_remote(self, remote: Any) -> FleetReplica:
        """Register one remote replica (ctor path and ``add_remote``
        share it): install the router's crash hook and wrap it in a
        ``FleetReplica`` with ``engine=None`` — every engine-shaped
        probe goes over the wire instead."""
        rid = self._next_rid
        self._next_rid += 1
        remote.on_crash = self._on_replica_crash
        rep = FleetReplica(rid=rid, engine=None, frontend=remote)
        self.replicas.append(rep)
        self._by_frontend[id(remote)] = rep
        return rep

    def add_remote(self, remote: Any) -> FleetReplica:
        """Join a replica that lives on the far side of the fleet wire:
        ``remote`` is a :class:`~.remote.RemoteReplica` (or anything
        satisfying the frontend surface). It takes the same
        ``FleetReplica`` slot an in-process frontend would — placement
        (health → prefix affinity → least-loaded), crash salvage, and
        migration all work unchanged. No EWMA warm-start: the remote's
        own frontend measures its own throughput."""
        rep = self._join_remote(remote)
        with self._lock:
            self.n_scale_up += 1
        telemetry.count("fleet/scale_up")
        telemetry.gauge("fleet/size", float(self.n_routable))
        logger.info(f"fleet scale-up: remote replica {rep.rid} "
                    f"({getattr(remote, 'label', '?')}) joined")
        return rep

    # --------------------------------------------------------- migration
    def _resolve_replica(self,
                         rep: Union[int, FleetReplica]) -> FleetReplica:
        if isinstance(rep, FleetReplica):
            return rep
        found = next((r for r in self.replicas if r.rid == rep), None)
        if found is None:
            raise MigrationError(f"unknown replica {rep!r}")
        return found

    def migrate(self, uid: int, src: Union[int, FleetReplica],
                dst: Union[int, FleetReplica]) -> bool:
        """Live KV-block migration: detach a RUNNING request from
        ``src`` (KV blocks + block table + decode cursor serialize into
        a bundle), re-home it mid-decode onto ``dst``, and keep the
        caller's SAME StreamHandle streaming — greedy bit-identical to
        never having moved, zero lost or duplicated tokens. This is the
        rebalancing verb: unlike crash replay nothing recomputes — the
        device state itself moves.

        On a destination failure the request is re-imported at the
        source (the export does not destroy state until the import
        lands... strictly: export+cancel, then best-effort restore), so
        a failed migration degrades to a load-balancing miss, never a
        lost stream. Returns True on success; failures count
        ``fleet/migrate_failed``."""
        src = self._resolve_replica(src)
        dst = self._resolve_replica(dst)
        t0 = self._clock()
        try:
            bundle, handle = src.frontend.migrate_out(uid)
        except MigrationError as e:
            self._record_migrate_failure(uid, src, dst, f"export: {e}")
            return False
        resumed = len(bundle["tokens"])
        try:
            dst.frontend.migrate_in(bundle, handle,
                                    migrated_from=str(src.rid))
        except MigrationError as e:
            # destination refused: put the request back where it was
            try:
                src.frontend.migrate_in(bundle, handle,
                                        migrated_from=None)
            except MigrationError as e2:
                handle._resolve(
                    "error",
                    error=f"migration failed both ways (dst: {e}; "
                          f"src restore: {e2})")
            self._record_migrate_failure(uid, src, dst, f"import: {e}")
            return False
        kv_bytes = int(bundle.get("kv_bytes", 0))
        telemetry.count("fleet/migrated")
        telemetry.count("fleet/migrate_bytes", float(kv_bytes))
        telemetry.instant("fleet/migration", trace_id=handle.trace_id,
                          from_replica=src.rid, to_replica=dst.rid,
                          resumed_tokens=resumed, kv_bytes=kv_bytes)
        with self._lock:
            self.n_migrated += 1
            self.migrate_bytes += kv_bytes
            self._migrations.append({
                "trace_id": handle.trace_id, "uid": int(uid),
                "t": t0, "dur_s": self._clock() - t0,
                "from_replica": src.rid, "to_replica": dst.rid,
                "resumed_tokens": resumed, "kv_bytes": kv_bytes})
        logger.info(f"fleet migration: uid={uid} replica {src.rid} -> "
                    f"{dst.rid} ({resumed} tokens resumed, "
                    f"{kv_bytes} KV bytes)")
        return True

    def _record_migrate_failure(self, uid: int, src: FleetReplica,
                                dst: FleetReplica, why: str) -> None:
        telemetry.count("fleet/migrate_failed")
        with self._lock:
            self.n_migrate_failed += 1
            self._migrations.append({
                "trace_id": None, "uid": int(uid), "t": self._clock(),
                "from_replica": src.rid, "to_replica": dst.rid,
                "failed": why})
        logger.warning(f"fleet migration uid={uid} "
                       f"{src.rid}->{dst.rid} failed: {why}")

    def rebalance(self, *, spread_threshold: int = 2,
                  max_moves: int = 1) -> List[Dict[str, Any]]:
        """One load-balancing pass: while the spread between the
        busiest and idlest routable replica's running count is at least
        ``spread_threshold``, migrate one movable request hot -> cold
        (up to ``max_moves``). Called periodically (benches, the
        elastic controller's optional hook) to keep per-replica
        occupancy spread bounded under skew — hot replicas rebalance
        instead of only shedding. Returns the move records."""
        moves: List[Dict[str, Any]] = []
        for _ in range(max(0, int(max_moves))):
            cands = [r for r in self.replicas if r.routable]
            if len(cands) < 2:
                break
            occ = {r.rid: int(r.frontend.load_snapshot()
                              .get("engine_running", 0)) for r in cands}
            hot = max(cands, key=lambda r: occ[r.rid])
            cold = min(cands, key=lambda r: occ[r.rid])
            if occ[hot.rid] - occ[cold.rid] < spread_threshold:
                break
            movable = hot.frontend.migration_candidates()
            if not movable:
                break
            uid = movable[0]
            ok = self.migrate(uid, hot, cold)
            moves.append({"uid": int(uid), "from_replica": hot.rid,
                          "to_replica": cold.rid, "ok": ok,
                          "spread": occ[hot.rid] - occ[cold.rid]})
            if not ok:
                break
        return moves

    def retire_replica(self, rid: Optional[int] = None, *,
                       min_routable: int = 1) -> Optional[FleetReplica]:
        """Shrink the fleet by one replica, gracefully: mark it
        ``draining`` (placement stops immediately; the driver keeps
        running so in-engine chunks retire naturally) and adopt its
        admission-pending tail onto survivors. Picks the
        least-loaded routable replica when ``rid`` is None. Refuses —
        returning None — when retirement would leave fewer than
        ``min_routable`` routable replicas. ``poll_draining()``
        finalizes the retirement once the replica is idle."""
        with self._lock:
            routable = [r for r in self.replicas if r.routable]
            if len(routable) <= min_routable:
                return None
            if rid is None:
                rep = min(routable, key=self._load_score)
            else:
                rep = next((r for r in routable if r.rid == rid), None)
                if rep is None:
                    return None
            rep.draining = True
            rep.frontend.draining = True   # /readyz mirrors the drain
            self.n_scale_down += 1
        telemetry.count("fleet/scale_down")
        telemetry.gauge("fleet/size", float(self.n_routable))
        # re-home the admission tail NOW — those requests never reached
        # the engine, so survivors can start them without replay
        tail = rep.frontend.drain_pending()
        logger.info(f"fleet scale-down: replica {rep.rid} draining "
                    f"({len(tail)} pending re-homed)")
        for handle in tail:
            self._reroute(handle, None, src_rid=rep.rid)
        return rep

    def poll_draining(self) -> List[int]:
        """Finalize retirements: close every draining replica that has
        gone idle (no pending admission, nothing queued or running in
        its engine) and mark it ``retired``. Returns the rids retired
        by this call. The elastic controller calls this each tick;
        tests/benches may call it directly."""
        retired: List[int] = []
        for rep in self.replicas:
            if not rep.draining or rep.retired or rep.dead:
                continue
            snap = rep.frontend.load_snapshot()
            if (snap["admission"]["pending"] == 0
                    and snap["engine_queue_depth"] == 0
                    and snap["engine_running"] == 0):
                rep.frontend.close(timeout=30.0)
                rep.retired = True
                with self._lock:
                    self.n_drained += 1
                telemetry.count("fleet/drained")
                logger.info(f"fleet replica {rep.rid} drained + retired")
                retired.append(rep.rid)
        return retired

    # ------------------------------------------------------- crash drain
    def _on_replica_crash(self, frontend: ServingFrontend,
                          salvaged: List[StreamHandle],
                          exc: BaseException) -> None:
        """``ServingFrontend`` crash hook (runs on the dead driver
        thread): mark the replica dead, record the crash (with the
        flight recorder's postmortem path), then re-home every salvaged
        still-unresolved handle on a survivor — never-prefilled work
        restarts from scratch, prefilled work replays."""
        with self._lock:
            rep = self._by_frontend.get(id(frontend))
            if rep is not None and not rep.dead:
                rep.dead = True
                self.n_replica_crashes += 1
        # the crashed frontend dumped its postmortem BEFORE invoking
        # this hook — attach its path to the crash + reroute records
        postmortem = getattr(frontend, "postmortem_path", None)
        # the dead driver thread carries its replica label; fleet-level
        # reroute counters must not inherit it
        with telemetry.replica_label(None):
            telemetry.count("fleet/replica_crashes")
            rid = rep.rid if rep is not None else "?"
            logger.error(
                f"fleet replica {rid} crashed "
                f"({type(exc).__name__}: {exc}); re-routing "
                f"{len(salvaged)} queued requests")
            with self._lock:
                self._crashes.append({
                    "replica": rid, "t": self._clock(),
                    "error": f"{type(exc).__name__}: {exc}",
                    "postmortem": postmortem,
                    "n_salvaged": len(salvaged)})
            for handle in salvaged:
                self._reroute(handle, exc, src_rid=rid,
                              postmortem=postmortem)

    def _reroute(self, handle: StreamHandle,
                 exc: Optional[BaseException] = None,
                 src_rid: Any = None,
                 postmortem: Optional[str] = None) -> None:
        """Re-home one handle on a survivor (crash drain AND graceful
        drain share this). A handle that already streamed tokens counts
        as a REPLAY — the survivor's ``adopt`` re-prefills prompt +
        emitted prefix and resumes the stream."""
        n_emitted = len(handle.tokens)
        target = self._place(handle._request.prompt)
        if target.alive and target.frontend.adopt(
                handle,
                rerouted_from=str(src_rid) if src_rid is not None
                else None):
            telemetry.count("fleet/rerouted")
            if n_emitted:
                telemetry.count("fleet/replayed")
            telemetry.instant("fleet/reroute", trace_id=handle.trace_id,
                              rerouted_from=src_rid,
                              rerouted_to=target.rid,
                              replayed_tokens=n_emitted)
            with self._lock:
                self.n_rerouted += 1
                if n_emitted:
                    self.n_replayed += 1
                self._reroutes.append({
                    "trace_id": handle.trace_id, "uid": handle.uid,
                    "t": self._clock(), "from_replica": src_rid,
                    "to_replica": target.rid,
                    "replayed_tokens": n_emitted,
                    "postmortem": postmortem})
            return
        with self._lock:
            self.n_reroute_failed += 1
        telemetry.count("fleet/reroute_failed")
        if not handle.done:   # adopt() resolves on its own rejections
            why = (f"replica crashed ({type(exc).__name__}: {exc})"
                   if exc is not None else
                   f"replica {src_rid} drained")
            handle._resolve(
                "error",
                error=f"{why} and no survivor accepted the request")

    # ----------------------------------------------------------- queries
    @property
    def n_alive(self) -> int:
        return sum(1 for r in self.replicas if r.alive)

    @property
    def n_routable(self) -> int:
        return sum(1 for r in self.replicas if r.routable)

    def stats(self) -> Dict[str, Any]:
        """Fleet-level counters plus every replica's own stats."""
        with self._lock:
            out: Dict[str, Any] = {
                "replicas": len(self.replicas),
                "alive": self.n_alive,
                "routable": self.n_routable,
                "draining": sum(1 for r in self.replicas if r.draining
                                and not r.retired),
                "retired": sum(1 for r in self.replicas if r.retired),
                "routed": self.n_routed,
                "affinity_hits": self.n_affinity_hits,
                "tier_fetches": self.n_tier_fetches,
                "rerouted": self.n_rerouted,
                "replayed": self.n_replayed,
                "reroute_failed": self.n_reroute_failed,
                "replica_crashes": self.n_replica_crashes,
                "scale_up": self.n_scale_up,
                "scale_down": self.n_scale_down,
                "drained": self.n_drained,
                "migrated": self.n_migrated,
                "migrate_failed": self.n_migrate_failed,
                "migrate_bytes": self.migrate_bytes,
                "crashes": [dict(c) for c in self._crashes],
            }
        out["per_replica"] = {
            r.rid: {"alive": r.alive, **r.frontend.stats()}
            for r in self.replicas}
        return out

    def tenants_report(self) -> Dict[str, Any]:
        """Fleet-wide per-tenant goodput: every replica's ``TraceLog``
        tenant aggregates plus merged token/goodput totals (reservoir
        percentiles don't merge — read them per replica)."""
        per_replica = {r.rid: r.frontend.tracing.tenants_report()
                       for r in self.replicas}
        merged: Dict[str, Dict[str, Any]] = {}
        for rep in per_replica.values():
            for tenant, t in rep.get("tenants", {}).items():
                m = merged.setdefault(tenant, {
                    "n_requests": 0, "total_tokens": 0,
                    "goodput_tokens": 0})
                m["n_requests"] += t.get("n_requests", 0)
                m["total_tokens"] += t.get("total_tokens", 0)
                m["goodput_tokens"] += t.get("goodput_tokens", 0)
        for m in merged.values():
            m["goodput_fraction"] = (
                m["goodput_tokens"] / m["total_tokens"]
                if m["total_tokens"] else 1.0)
        return {
            "schema": "dstpu-fleet-tenants-v1",
            "n_tenants": len(merged),
            "tenants": merged,
            "per_replica": per_replica,
        }

    # ----------------------------------------------------------- journeys
    def journey_journal(self) -> Dict[str, Any]:
        """The router's journey input for ``telemetry.journey``:
        placement / reroute / crash records plus every replica's
        ``TraceLog.to_json()``."""
        with self._lock:
            journal: Dict[str, Any] = {
                "placements": [dict(p) for p in self._placements],
                "reroutes": [dict(r) for r in self._reroutes],
                "crashes": [dict(c) for c in self._crashes],
                "migrations": [dict(m) for m in self._migrations],
            }
        journal["replicas"] = {r.rid: r.frontend.tracing.to_json()
                               for r in self.replicas}
        return journal

    def export_chrome(self, path: Optional[str] = None,
                      runtime=None) -> Dict[str, Any]:
        """One Perfetto file for the whole fleet: the shared telemetry
        runtime (per-replica driver threads, pid 1), every replica's
        per-request lanes (pid 2 — a rerouted uid's two segments share
        one lane), and one journey lane per trace id (pid 3) with
        placement + reroute flow arrows. Writes to ``path`` when given;
        always returns the trace object."""
        from ...telemetry import (chrome_trace, request_trace_events,
                                  write_chrome_trace)
        from ...telemetry import core as _tcore
        rt = runtime if runtime is not None else _tcore.get_runtime()
        journal = self.journey_journal()
        extra: List[dict] = []
        for rid in sorted(journal["replicas"]):
            extra.extend(request_trace_events(journal["replicas"][rid]))
        extra.extend(journey_trace_events(journal))
        if path is None:
            return chrome_trace(rt, extra_events=extra)
        return write_chrome_trace(path, rt, extra_events=extra)
