"""FleetRouter: one submit() surface over N data-parallel serving
replicas.

One continuous-batching :class:`~deepspeed_tpu.serving.engine
.ServingEngine` saturates one mesh slice; a serving deployment runs
several — data-parallel replicas with identical weights — behind one
frontend. This module is that frontend-of-frontends. Each replica is a
``ServingEngine`` owned by its own :class:`ServingFrontend` (its own
daemon driver thread, admission controller, throughput estimator), and
the router only ever makes PLACEMENT decisions; after placement the
request's whole lifecycle — admission, prefill, decode chunks, token
streaming — is the chosen replica's, and the caller holds a perfectly
ordinary :class:`StreamHandle`.

Placement, in order:

1. **Health**: replicas whose driver thread has crashed (or that the
   router already marked dead) never receive traffic — the
   ``HealthMonitor`` contract ("a fleet router should stop placing
   traffic here") enforced at the router.
2. **Prefix affinity**: hash the prompt (``PrefixCache.key_for`` — the
   exact token-byte key the paged allocator uses) and prefer replicas
   whose :class:`PrefixCache` already holds it: a hit replica serves
   the prompt's prefill almost for free by block sharing, so sending
   the request anywhere else throws away cached device work. The probe
   is a pure peek (no LRU refresh, no counters).
3. **Least loaded**: among the remaining candidates, pick the lowest
   estimated drain time — outstanding work from the frontend's locked
   ``load_snapshot()`` (admission-pending + engine backlog tokens)
   over the replica's EWMA decode throughput.

**Dead-replica drain**: each frontend gets the router as its
``on_crash`` hook. When a driver crashes, work that never touched the
device (admission-pending tickets, engine-queued requests) is re-homed
on surviving replicas via ``ServingFrontend.adopt`` — the SAME handle
objects keep streaming to their callers — while prefilled/running
requests still resolve ``error`` (their KV state died with the
replica). The crashed replica is marked dead and drops out of
placement.

Telemetry: every replica's driver thread is labeled (``replica=<id>``
via ``telemetry.replica_label``) so per-replica gauges/counters stay
distinguishable in one process-wide runtime; the router's own counters
(``fleet/routed``, ``fleet/affinity_hits``, ``fleet/rerouted``,
``fleet/reroute_failed``, ``fleet/replica_crashes``) are recorded
unlabeled — they are fleet-level, not per-replica.

Host-side only — this module never imports JAX.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ...telemetry import core as telemetry
from ...telemetry.journey import journey_trace_events, new_trace_id
from ...utils.logging import logger
from ..frontend.admission import AdmissionConfig, PRIORITY_NORMAL
from ..frontend.frontend import ServingFrontend, StreamHandle
from ..paged_kv import PrefixCache


@dataclasses.dataclass
class FleetReplica:
    """One replica's slot in the fleet: engine + owning frontend +
    router-side health mark."""
    rid: int
    engine: Any
    frontend: ServingFrontend
    dead: bool = False

    @property
    def alive(self) -> bool:
        return not self.dead and self.frontend.driver_alive


class FleetRouter:
    """Route requests across N ``ServingEngine`` replicas.

    ``engines`` are pre-built replicas (identical weights — the router
    assumes any replica can serve any request). Each is wrapped in a
    ``ServingFrontend`` with its own driver thread; the router owns
    those frontends and ``close()`` drains all of them. ``admission``
    is copied per replica (the frontend mutates its config in place to
    size memory-aware shedding from the engine arena).
    """

    def __init__(self, engines: Sequence[Any], *,
                 admission: Optional[AdmissionConfig] = None,
                 affinity: bool = True,
                 feed_depth: Optional[int] = None,
                 idle_wait_s: float = 0.005,
                 clock=time.monotonic):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self._clock = clock
        self.affinity = bool(affinity)
        self._lock = threading.Lock()
        self.n_routed = 0
        self.n_affinity_hits = 0
        self.n_rerouted = 0
        self.n_reroute_failed = 0
        self.n_replica_crashes = 0
        # journey journal: placement / reroute / crash records under one
        # trace id per request — the input to ``export_chrome``'s
        # journey lanes and the roadmap's future replay loop (bounded:
        # a long-running router never grows without bound)
        self._placements: deque = deque(maxlen=4096)
        self._reroutes: deque = deque(maxlen=1024)
        self._crashes: deque = deque(maxlen=256)
        self.replicas: List[FleetReplica] = []
        self._by_frontend: Dict[int, FleetReplica] = {}
        for rid, eng in enumerate(engines):
            cfg = dataclasses.replace(admission) if admission is not None \
                else None
            fe = ServingFrontend(eng, admission=cfg,
                                 feed_depth=feed_depth,
                                 idle_wait_s=idle_wait_s,
                                 on_crash=self._on_replica_crash,
                                 telemetry_label=str(rid),
                                 clock=clock)
            rep = FleetReplica(rid=rid, engine=eng, frontend=fe)
            self.replicas.append(rep)
            self._by_frontend[id(fe)] = rep

    # ------------------------------------------------------- public API
    def submit(self, prompt: Union[Sequence[int], np.ndarray], *,
               priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> StreamHandle:
        """Place one request and enqueue it; returns the chosen
        replica's StreamHandle immediately. With every replica dead the
        handle resolves ``rejected`` (``frontend_closed``) — same
        no-exception contract as ``ServingFrontend.submit``.

        Every submit mints a ``trace_id`` that rides the handle, the
        admission ticket, the engine request, and the chosen replica's
        trace segment; the placement decision (candidate scores,
        affinity hit, chosen replica) is journaled under that id."""
        trace_id = new_trace_id()
        t0 = self._clock()
        replica, decision = self._place_decision(prompt)
        t1 = self._clock()
        telemetry.count("fleet/routed")
        with self._lock:
            self.n_routed += 1
        handle = replica.frontend.submit(
            prompt, priority=priority, tenant=tenant,
            slo_ttft_s=slo_ttft_s, deadline_s=deadline_s,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            trace_id=trace_id)
        telemetry.instant("fleet/placement", trace_id=trace_id,
                          replica=replica.rid,
                          affinity_hit=decision["affinity_hit"])
        with self._lock:
            self._placements.append({
                "trace_id": trace_id, "uid": handle.uid, "t": t0,
                "dur_s": t1 - t0, "replica": replica.rid,
                "affinity_hit": decision["affinity_hit"],
                "scores": decision["scores"],
                "candidates": decision["candidates"]})
        return handle

    def close(self, timeout: Optional[float] = None) -> None:
        for rep in self.replicas:
            rep.frontend.close(timeout)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- placement
    def _place(self, prompt) -> FleetReplica:
        return self._place_decision(prompt)[0]

    def _place_decision(self, prompt) -> Tuple[FleetReplica,
                                               Dict[str, Any]]:
        """Choose a replica AND return the decision record (candidate
        rids, per-candidate load scores, affinity hit) that the journey
        journal attaches to the request's ``route`` span."""
        decision: Dict[str, Any] = {"affinity_hit": False, "scores": {},
                                    "candidates": []}
        candidates = [r for r in self.replicas if r.alive]
        if not candidates:
            # every replica is dead: any frontend will reject-with-reason
            # (frontend_closed) — deliberate, so callers get a terminal
            # handle instead of an exception
            return self.replicas[0], decision
        if self.affinity and len(candidates) > 1:
            key = PrefixCache.key_for(prompt)
            hits = [r for r in candidates if self._holds_prefix(r, key)]
            if hits:
                telemetry.count("fleet/affinity_hits")
                with self._lock:
                    self.n_affinity_hits += 1
                candidates = hits
                decision["affinity_hit"] = True
        decision["candidates"] = [r.rid for r in candidates]
        if len(candidates) == 1:
            return candidates[0], decision
        scores = {r.rid: self._load_score(r) for r in candidates}
        decision["scores"] = scores
        return min(candidates, key=lambda r: scores[r.rid]), decision

    @staticmethod
    def _holds_prefix(replica: FleetReplica, key: bytes) -> bool:
        kv = getattr(replica.engine, "kv", None)
        if kv is None or not getattr(kv, "prefix_enabled", False):
            return False
        return key in kv.prefix_cache

    @staticmethod
    def _load_score(replica: FleetReplica) -> float:
        """Estimated drain time: outstanding tokens over EWMA decode
        throughput. Admission-pending requests haven't sized their
        decode yet, so they count by the engine-side backlog convention
        (prompt + budget) folded into ``pending`` as request counts —
        with homogeneous data-parallel replicas the ordering is what
        matters, not the absolute seconds."""
        snap = replica.frontend.load_snapshot()
        outstanding = (float(snap["engine_backlog_tokens"])
                       + float(snap["admission"]["pending"]))
        rate = snap["throughput"]["tokens_per_s"]
        return outstanding / rate if rate else outstanding

    # ------------------------------------------------------- crash drain
    def _on_replica_crash(self, frontend: ServingFrontend,
                          salvaged: List[StreamHandle],
                          exc: BaseException) -> None:
        """``ServingFrontend`` crash hook (runs on the dead driver
        thread): mark the replica dead, record the crash (with the
        flight recorder's postmortem path), then re-home every salvaged
        — never-prefilled, still-unresolved — handle on a survivor."""
        with self._lock:
            rep = self._by_frontend.get(id(frontend))
            if rep is not None and not rep.dead:
                rep.dead = True
                self.n_replica_crashes += 1
        # the crashed frontend dumped its postmortem BEFORE invoking
        # this hook — attach its path to the crash + reroute records
        postmortem = getattr(frontend, "postmortem_path", None)
        # the dead driver thread carries its replica label; fleet-level
        # reroute counters must not inherit it
        with telemetry.replica_label(None):
            telemetry.count("fleet/replica_crashes")
            rid = rep.rid if rep is not None else "?"
            logger.error(
                f"fleet replica {rid} crashed "
                f"({type(exc).__name__}: {exc}); re-routing "
                f"{len(salvaged)} queued requests")
            with self._lock:
                self._crashes.append({
                    "replica": rid, "t": self._clock(),
                    "error": f"{type(exc).__name__}: {exc}",
                    "postmortem": postmortem,
                    "n_salvaged": len(salvaged)})
            for handle in salvaged:
                self._reroute(handle, exc, src_rid=rid,
                              postmortem=postmortem)

    def _reroute(self, handle: StreamHandle, exc: BaseException,
                 src_rid: Any = None,
                 postmortem: Optional[str] = None) -> None:
        target = self._place(handle._request.prompt)
        if target.alive and target.frontend.adopt(
                handle,
                rerouted_from=str(src_rid) if src_rid is not None
                else None):
            telemetry.count("fleet/rerouted")
            telemetry.instant("fleet/reroute", trace_id=handle.trace_id,
                              rerouted_from=src_rid,
                              rerouted_to=target.rid)
            with self._lock:
                self.n_rerouted += 1
                self._reroutes.append({
                    "trace_id": handle.trace_id, "uid": handle.uid,
                    "t": self._clock(), "from_replica": src_rid,
                    "to_replica": target.rid, "postmortem": postmortem})
            return
        with self._lock:
            self.n_reroute_failed += 1
        telemetry.count("fleet/reroute_failed")
        if not handle.done:   # adopt() resolves on its own rejections
            handle._resolve(
                "error",
                error=f"replica crashed ({type(exc).__name__}: {exc}) "
                      f"and no survivor accepted the request")

    # ----------------------------------------------------------- queries
    @property
    def n_alive(self) -> int:
        return sum(1 for r in self.replicas if r.alive)

    def stats(self) -> Dict[str, Any]:
        """Fleet-level counters plus every replica's own stats."""
        with self._lock:
            out: Dict[str, Any] = {
                "replicas": len(self.replicas),
                "alive": self.n_alive,
                "routed": self.n_routed,
                "affinity_hits": self.n_affinity_hits,
                "rerouted": self.n_rerouted,
                "reroute_failed": self.n_reroute_failed,
                "replica_crashes": self.n_replica_crashes,
                "crashes": [dict(c) for c in self._crashes],
            }
        out["per_replica"] = {
            r.rid: {"alive": r.alive, **r.frontend.stats()}
            for r in self.replicas}
        return out

    # ----------------------------------------------------------- journeys
    def journey_journal(self) -> Dict[str, Any]:
        """The router's journey input for ``telemetry.journey``:
        placement / reroute / crash records plus every replica's
        ``TraceLog.to_json()``."""
        with self._lock:
            journal: Dict[str, Any] = {
                "placements": [dict(p) for p in self._placements],
                "reroutes": [dict(r) for r in self._reroutes],
                "crashes": [dict(c) for c in self._crashes],
            }
        journal["replicas"] = {r.rid: r.frontend.tracing.to_json()
                               for r in self.replicas}
        return journal

    def export_chrome(self, path: Optional[str] = None,
                      runtime=None) -> Dict[str, Any]:
        """One Perfetto file for the whole fleet: the shared telemetry
        runtime (per-replica driver threads, pid 1), every replica's
        per-request lanes (pid 2 — a rerouted uid's two segments share
        one lane), and one journey lane per trace id (pid 3) with
        placement + reroute flow arrows. Writes to ``path`` when given;
        always returns the trace object."""
        from ...telemetry import (chrome_trace, request_trace_events,
                                  write_chrome_trace)
        from ...telemetry import core as _tcore
        rt = runtime if runtime is not None else _tcore.get_runtime()
        journal = self.journey_journal()
        extra: List[dict] = []
        for rid in sorted(journal["replicas"]):
            extra.extend(request_trace_events(journal["replicas"][rid]))
        extra.extend(journey_trace_events(journal))
        if path is None:
            return chrome_trace(rt, extra_events=extra)
        return write_chrome_trace(path, rt, extra_events=extra)
