"""Hierarchical fleet routing: pods of replicas behind one root.

One flat :class:`~.router.FleetRouter` scores every replica per submit
— O(N) probes from one process's view, the structural ceiling on the
ROADMAP's millions-of-users north star. This module splits placement
into two levels:

* **LeafRouter** — a ``FleetRouter`` that owns one *pod* of replicas.
  Per-pod placement (health → prefix affinity → least-loaded) is
  exactly today's policy, unchanged; the leaf additionally publishes a
  cached pod-level aggregate (``pod_snapshot``) and, when a crash or
  drain leaves the pod with no routable survivor, escalates the
  re-home to the root instead of erroring the stream.
* **RootRouter** — places by pod-level aggregates only. A consistent-
  hash ring (stable blake2b digest, virtual nodes) maps the prompt's
  prefix key to a pod, so prefix affinity survives WITHOUT probing
  every replica's cache: all repeats of a hot prompt land in one pod
  and the leaf's existing affinity probe finds the holder among a
  bounded pod-sized candidate set. Pod join/leave moves only the
  minimal key range (the ring property), adapter/tenant pins override
  the ring, and global admission sheds at the edge — an overloaded pod
  rejects the request up front (``pod_overloaded``) instead of
  queueing it into a doomed backlog.

``migrate()``/``rebalance()`` generalize the flat router's live
KV-block migration to cross-pod moves: the bundle exports from the
source pod's replica and imports into the destination pod's over the
same ``dstpu-fleet-v1`` surface (in-process or remote — the frontends
are interchangeable). The ``elasticity/`` heritage wires in as
*per-pod* policy: each pod gets its own
:class:`~.elastic.ElasticController` scaling off that pod's own
sensors, while the root only adds/retires whole pods
(``add_pod``/``retire_pod``).

Telemetry: pod-labelled gauges ride the embedded-label mechanism the
tenant/replica series use (``fleet/pod_drain_s|pod=<id>``); root-level
counters (``fleet/pod_shed``, ``fleet/pod_spill``,
``fleet/pod_failover``, ``fleet/cross_pod_migrated``,
``fleet/pod_lost``, ``fleet/pod_retired``) are fleet-wide. Journey
hops are pod-qualified (``<pod>/<rid>``) in the merged journal.

Host-side only — this module never imports JAX.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ...analysis import locks
from ...telemetry import core as telemetry
from ...telemetry.journey import new_trace_id
from ...utils.logging import logger
from ..engine import MigrationError
from ..frontend.admission import PRIORITY_NORMAL
from ..frontend.frontend import StreamHandle
from ..paged_kv import PrefixCache
from ..scheduler import Request
from .elastic import ElasticConfig, ElasticController
from .router import FleetReplica, FleetRouter

#: machine-readable rejection reason for edge shedding: every pod the
#: ring (plus spill) offered was over its admission bar, so the root
#: rejected at the edge instead of queueing into a doomed backlog.
REJECT_POD_OVERLOADED = "pod_overloaded"


class ConsistentHashRing:
    """Consistent hashing with virtual nodes over a stable digest.

    Points come from blake2b — never Python ``hash()``, whose
    per-process randomization (PYTHONHASHSEED) would scatter a fleet's
    placement across restarts and processes. Each pod contributes
    ``vnodes`` points; a key maps to the first pod point at or after
    its own point (wrapping). Adding/removing one pod therefore moves
    only the key ranges adjacent to that pod's points — about
    ``1/pods`` of the keyspace — and nothing else.
    """

    def __init__(self, *, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._points: List[int] = []      # sorted vnode points
        self._owners: List[str] = []      # _owners[i] owns _points[i]
        self._pods: Dict[str, List[int]] = {}

    @staticmethod
    def point(data: bytes) -> int:
        """Stable 64-bit ring point for arbitrary bytes."""
        return int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "big")

    def __len__(self) -> int:
        return len(self._pods)

    def __contains__(self, pod_id: str) -> bool:
        return pod_id in self._pods

    @property
    def pods(self) -> List[str]:
        return sorted(self._pods)

    def add_pod(self, pod_id: str) -> None:
        pod_id = str(pod_id)
        if pod_id in self._pods:
            return
        pts = []
        for i in range(self.vnodes):
            p = self.point(f"{pod_id}#{i}".encode("utf-8"))
            idx = bisect.bisect_left(self._points, p)
            # digest collisions across distinct vnode labels are
            # ~2^-64; skip rather than silently double-own a point
            if idx < len(self._points) and self._points[idx] == p:
                continue
            self._points.insert(idx, p)
            self._owners.insert(idx, pod_id)
            pts.append(p)
        self._pods[pod_id] = pts

    def remove_pod(self, pod_id: str) -> None:
        pts = self._pods.pop(str(pod_id), None)
        if pts is None:
            return
        for p in pts:
            idx = bisect.bisect_left(self._points, p)
            if idx < len(self._points) and self._points[idx] == p:
                del self._points[idx]
                del self._owners[idx]

    def pod_for(self, key: bytes) -> Optional[str]:
        """Owner pod of ``key``, or None on an empty ring."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, self.point(key))
        if idx == len(self._points):
            idx = 0
        return self._owners[idx]

    def pods_for(self, key: bytes, n: int) -> List[str]:
        """First ``n`` DISTINCT pods walking the ring clockwise from
        ``key`` — the primary owner first, then spill candidates in
        deterministic ring order."""
        if not self._points or n < 1:
            return []
        out: List[str] = []
        start = bisect.bisect_right(self._points, self.point(key))
        for off in range(len(self._points)):
            owner = self._owners[(start + off) % len(self._points)]
            if owner not in out:
                out.append(owner)
                if len(out) >= n:
                    break
        return out


class LeafRouter(FleetRouter):
    """One pod's ``FleetRouter``: flat placement within the pod,
    plus the pod-aggregate surface the root places by.

    ``pod_snapshot()`` is cached for ``agg_ttl_s`` (on the router's
    injectable clock, so simulators stay deterministic): the root's
    per-submit overload check costs O(1) amortized instead of
    re-probing the pod. ``add_replica`` additionally accepts a factory
    that yields a frontend-surface object (``submit``/``driver_alive``)
    — a ``RemoteReplica`` or a sim replica — joining it via the remote
    path, so per-pod elastic growth works for every replica flavor.
    """

    def __init__(self, pod_id: str, engines: Sequence[Any] = (), *,
                 agg_ttl_s: float = 0.05, **kwargs):
        self.pod_id = str(pod_id)
        self.agg_ttl_s = float(agg_ttl_s)
        self._root: Optional["RootRouter"] = None
        self._agg_lock = locks.make_lock("fleet.leaf_agg")
        self._agg: Optional[Dict[str, Any]] = None
        self._agg_t: float = float("-inf")
        super().__init__(engines, **kwargs)

    # ----------------------------------------------------- pod aggregate
    def pod_snapshot(self, *,
                     max_age_s: Optional[float] = None) -> Dict[str, Any]:
        """Pod-level placement aggregate: routable count, admission
        pending, outstanding engine tokens, summed throughput, and the
        derived drain-time estimate. Cached for ``agg_ttl_s`` (override
        with ``max_age_s``; 0 forces a fresh probe)."""
        ttl = self.agg_ttl_s if max_age_s is None else float(max_age_s)
        now = self._clock()
        with self._agg_lock:
            if self._agg is not None and now - self._agg_t < ttl:
                return self._agg
        reps = [r for r in self.replicas if r.routable]
        pending = 0
        backlog = 0.0
        rate = 0.0
        for r in reps:
            snap = r.frontend.load_snapshot()
            pending += int(snap["admission"]["pending"])
            backlog += float(snap["engine_backlog_tokens"])
            tps = snap["throughput"]["tokens_per_s"]
            if tps:
                rate += float(tps)
        outstanding = backlog + pending
        agg = {
            "pod": self.pod_id,
            "routable": len(reps),
            "pending": pending,
            "backlog_tokens": backlog,
            "tokens_per_s": rate or None,
            "drain_s": outstanding / rate if rate else outstanding,
        }
        with self._agg_lock:
            self._agg = agg
            self._agg_t = now
        telemetry.gauge(f"fleet/pod_routable|pod={self.pod_id}",
                        float(agg["routable"]))
        telemetry.gauge(f"fleet/pod_drain_s|pod={self.pod_id}",
                        float(agg["drain_s"]))
        telemetry.gauge(f"fleet/pod_backlog_tokens|pod={self.pod_id}",
                        float(agg["backlog_tokens"]))
        return agg

    # ------------------------------------------------------- elasticity
    def add_replica(self, engine: Any = None, *,
                    warm_start: bool = True) -> FleetReplica:
        if engine is None:
            if self.replica_factory is None:
                raise ValueError(
                    "add_replica() needs an engine or a replica_factory")
            engine = self.replica_factory()
        if hasattr(engine, "submit") and hasattr(engine, "driver_alive"):
            # frontend-surface product (RemoteReplica / SimReplica):
            # join it on the remote path — no in-process driver thread
            return self.add_remote(engine)
        return super().add_replica(engine, warm_start=warm_start)

    # ------------------------------------------------------ crash drain
    def _reroute(self, handle: StreamHandle,
                 exc: Optional[BaseException] = None,
                 src_rid: Any = None,
                 postmortem: Optional[str] = None) -> None:
        """Pod-local re-home first; when the whole pod is down (pod
        loss), escalate to the root so a survivor pod adopts the
        stream instead of erroring it."""
        if (self._root is not None
                and not any(r.routable for r in self.replicas)):
            if self._root._adopt_foreign(handle, src_pod=self.pod_id,
                                         src_rid=src_rid, exc=exc):
                return
        super()._reroute(handle, exc, src_rid=src_rid,
                         postmortem=postmortem)


@dataclasses.dataclass
class RootConfig:
    """Root placement policy knobs.

    ``shed_drain_s``/``shed_pending`` arm global admission: a pod whose
    estimated drain time (or admission-pending count) exceeds the bar
    is *overloaded* and the root spills to the next ``spill`` distinct
    pods on the ring before shedding at the edge. Both None (default)
    means never shed on load — only a pod with zero routable replicas
    is skipped. ``agg_ttl_s`` is the default pod-aggregate cache age a
    newly added ``LeafRouter`` is built with (pre-built leaves keep
    their own)."""
    vnodes: int = 64
    spill: int = 2
    shed_drain_s: Optional[float] = None
    shed_pending: Optional[int] = None
    agg_ttl_s: float = 0.05


class RootRouter:
    """Two-level fleet placement: consistent-hash prefix→pod, then the
    pod's own ``LeafRouter`` picks the replica.

    The root never probes individual replicas: its per-submit work is
    one ring lookup plus O(spill) cached pod aggregates — flat in
    fleet size. ``submit`` matches ``FleetRouter.submit`` (plus
    ``adapter=``); the returned handle is the leaf replica's ordinary
    ``StreamHandle``, or an edge-rejected one (``pod_overloaded``)
    when global admission sheds."""

    def __init__(self, *, config: Optional[RootConfig] = None,
                 elastic: Optional[ElasticConfig] = None,
                 clock=time.monotonic):
        self.config = config or RootConfig()
        self._clock = clock
        self._elastic = elastic
        self._lock = locks.make_lock("fleet.hierarchy")
        self._ring = ConsistentHashRing(vnodes=self.config.vnodes)
        self.pods: Dict[str, LeafRouter] = {}
        self.controllers: Dict[str, ElasticController] = {}
        # adapter/tenant affinity pins: a pinned id overrides the ring
        # (LoRA adapters resident in one pod; a tenant's dedicated pod)
        self._tenant_pins: Dict[str, str] = {}
        self._adapter_pins: Dict[str, str] = {}
        self._retiring: set = set()
        self._lost: set = set()
        self.n_routed = 0
        self.n_shed = 0
        self.n_spilled = 0
        self.n_pod_failover = 0
        self.n_cross_migrated = 0
        self.n_cross_migrate_failed = 0
        self.cross_migrate_bytes = 0
        self.n_pods_lost = 0
        self.n_pods_retired = 0
        self._placements: deque = deque(maxlen=4096)
        self._reroutes: deque = deque(maxlen=1024)
        self._migrations: deque = deque(maxlen=1024)
        # fleet observability plane (serve_metrics()): the root owns
        # the aggregator + its MetricsServer so close() tears them down
        self._fleet_agg = None
        self._metrics_server = None

    # ------------------------------------------------------ pod lifecycle
    def add_pod(self, pod_id: str, *, engines: Sequence[Any] = (),
                remotes: Optional[Sequence[Any]] = None,
                leaf: Optional[LeafRouter] = None,
                **leaf_kwargs) -> LeafRouter:
        """Join one pod: either a pre-built ``LeafRouter`` (``leaf=``)
        or one constructed here from ``engines``/``remotes``. The ring
        gains the pod's virtual nodes (moving ~1/pods of the keyspace
        onto it); with an ``elastic`` template the pod gets its own
        ``ElasticController`` stepping off its own sensors."""
        pod_id = str(pod_id)
        if pod_id in self.pods:
            raise ValueError(f"pod {pod_id!r} already joined")
        if leaf is None:
            leaf = LeafRouter(pod_id, engines, remotes=remotes,
                              agg_ttl_s=self.config.agg_ttl_s,
                              clock=self._clock, **leaf_kwargs)
        leaf._root = self
        self.pods[pod_id] = leaf
        self._ring.add_pod(pod_id)
        with self._lock:
            self._lost.discard(pod_id)
        if self._elastic is not None:
            self.controllers[pod_id] = ElasticController(
                leaf, dataclasses.replace(self._elastic),
                clock=self._clock)
        telemetry.count("fleet/pod_join")
        telemetry.gauge("fleet/pods", float(len(self.pods)))
        logger.info(f"fleet pod {pod_id} joined "
                    f"({len(leaf.replicas)} replicas)")
        return leaf

    def retire_pod(self, pod_id: str) -> bool:
        """Gracefully drain one pod out of the fleet: its key range
        redistributes to the survivors (minimal movement), every
        replica drains, and admission tails re-home cross-pod through
        the failover path. ``poll_retiring()`` finalizes."""
        pod_id = str(pod_id)
        leaf = self.pods.get(pod_id)
        with self._lock:
            if leaf is None or pod_id in self._retiring:
                return False
            self._retiring.add(pod_id)
        self._ring.remove_pod(pod_id)
        self.controllers.pop(pod_id, None)
        for rep in list(leaf.replicas):
            if rep.routable:
                leaf.retire_replica(rep.rid, min_routable=0)
        telemetry.count("fleet/pod_retiring")
        logger.info(f"fleet pod {pod_id} retiring")
        return True

    def poll_retiring(self) -> List[str]:
        """Finalize pod retirements whose replicas have all drained;
        returns the pod ids removed by this call."""
        done: List[str] = []
        with self._lock:
            retiring = list(self._retiring)
        for pod_id in retiring:
            leaf = self.pods.get(pod_id)
            if leaf is None:
                with self._lock:
                    self._retiring.discard(pod_id)
                continue
            leaf.poll_draining()
            if any(r.alive and not r.retired for r in leaf.replicas):
                continue
            leaf.close(timeout=5.0)
            del self.pods[pod_id]
            with self._lock:
                self._retiring.discard(pod_id)
                self.n_pods_retired += 1
            telemetry.count("fleet/pod_retired")
            telemetry.gauge("fleet/pods", float(len(self.pods)))
            logger.info(f"fleet pod {pod_id} retired")
            done.append(pod_id)
        return done

    def mark_pod_lost(self, pod_id: str) -> bool:
        """Abrupt pod loss (chaos, rack failure): the pod leaves the
        ring immediately so fresh placements stop landing on it;
        in-flight streams re-home through the crash-salvage path."""
        pod_id = str(pod_id)
        with self._lock:
            if pod_id not in self.pods or pod_id in self._lost:
                return False
            self._lost.add(pod_id)
            self.n_pods_lost += 1
            placeable = len(self.pods) - len(self._lost)
        self._ring.remove_pod(pod_id)
        self.controllers.pop(pod_id, None)
        telemetry.count("fleet/pod_lost")
        telemetry.gauge("fleet/pods", float(placeable))
        logger.error(f"fleet pod {pod_id} lost")
        return True

    def step(self) -> Dict[str, Any]:
        """One root control tick: step every pod's elastic controller,
        finalize pod retirements, and auto-detect lost pods (a pod
        with zero alive replicas leaves the ring)."""
        for pod_id, leaf in list(self.pods.items()):
            with self._lock:
                skip = pod_id in self._lost or pod_id in self._retiring
            if skip:
                continue
            if not any(r.alive for r in leaf.replicas):
                self.mark_pod_lost(pod_id)
        records = {pod_id: ctrl.step()
                   for pod_id, ctrl in list(self.controllers.items())}
        retired = self.poll_retiring()
        with self._lock:
            lost = sorted(self._lost)
        return {"pods": len(self.pods), "lost": lost,
                "retired": retired, "elastic": records}

    # --------------------------------------------------- affinity pins
    def pin_tenant(self, tenant: str, pod_id: Optional[str]) -> None:
        """Pin (or with None, unpin) a tenant's placements to one pod."""
        if pod_id is None:
            self._tenant_pins.pop(tenant, None)
        else:
            self._tenant_pins[tenant] = str(pod_id)

    def pin_adapter(self, adapter: str, pod_id: Optional[str]) -> None:
        """Pin (or with None, unpin) an adapter's placements to one pod
        — LoRA-style adapters resident in one pod's HBM route there."""
        if pod_id is None:
            self._adapter_pins.pop(adapter, None)
        else:
            self._adapter_pins[adapter] = str(pod_id)

    # --------------------------------------------------------- placement
    def _placeable(self, pod_id: str) -> Optional[LeafRouter]:
        with self._lock:
            if pod_id in self._lost or pod_id in self._retiring:
                return None
        return self.pods.get(pod_id)

    def _overloaded(self, leaf: LeafRouter) -> bool:
        snap = leaf.pod_snapshot()
        if snap["routable"] == 0:
            return True
        cfg = self.config
        if cfg.shed_pending is not None \
                and snap["pending"] >= cfg.shed_pending:
            return True
        if cfg.shed_drain_s is not None \
                and snap["drain_s"] > cfg.shed_drain_s:
            return True
        return False

    def _pod_order(self, prompt, tenant: str,
                   adapter: Optional[str]
                   ) -> Tuple[List[str], str, str]:
        """Candidate pods in preference order: adapter pin, tenant pin,
        then ring order from the prompt's prefix key (primary + spill
        successors). Returns ``(order, ring_key_hex, pin_source)`` —
        the placement provenance the journey journal records
        (``pin_source`` is ``"adapter"``/``"tenant"``/``"ring"``)."""
        order: List[str] = []
        pin_source = "ring"
        pin = self._adapter_pins.get(adapter) if adapter else None
        if pin is not None:
            pin_source = "adapter"
        else:
            pin = self._tenant_pins.get(tenant)
            if pin is not None:
                pin_source = "tenant"
        if pin is not None:
            order.append(pin)
        key = PrefixCache.key_for(prompt)
        for pod_id in self._ring.pods_for(key, 1 + self.config.spill):
            if pod_id not in order:
                order.append(pod_id)
        return order, key.hex()[:16], pin_source

    def submit(self, prompt: Union[Sequence[int], np.ndarray], *,
               priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               adapter: Optional[str] = None,
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> StreamHandle:
        """Place one request through the hierarchy. Never raises: with
        every candidate pod overloaded (or no pod at all) the handle
        resolves ``rejected`` (``pod_overloaded``) at the edge."""
        t0 = self._clock()
        order, ring_key, pin_source = self._pod_order(prompt, tenant,
                                                      adapter)
        chosen: Optional[LeafRouter] = None
        spilled = False
        spill_index = 0
        for i, pod_id in enumerate(order):
            leaf = self._placeable(pod_id)
            if leaf is None:
                continue
            if self._overloaded(leaf):
                continue
            chosen, spilled, spill_index = leaf, i > 0, i
            break
        if chosen is None:
            return self._shed(prompt, tenant=tenant, priority=priority,
                              slo_ttft_s=slo_ttft_s,
                              max_new_tokens=max_new_tokens, t0=t0,
                              tried=order, ring_key=ring_key,
                              pin_source=pin_source)
        handle = chosen.submit(
            prompt, priority=priority, tenant=tenant,
            slo_ttft_s=slo_ttft_s, deadline_s=deadline_s,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
        t1 = self._clock()
        telemetry.count(f"fleet/pod_routed|pod={chosen.pod_id}")
        if spilled:
            telemetry.count("fleet/pod_spill")
        with self._lock:
            self.n_routed += 1
            if spilled:
                self.n_spilled += 1
            self._placements.append({
                "trace_id": handle.trace_id, "uid": handle.uid,
                "t": t0, "dur_s": t1 - t0, "pod": chosen.pod_id,
                "spilled": spilled, "ring_key": ring_key,
                "pin": pin_source, "tried": order[:spill_index]})
        return handle

    def _shed(self, prompt, *, tenant: str, priority: int,
              slo_ttft_s: Optional[float], max_new_tokens: int,
              t0: float, tried: List[str], ring_key: str = "",
              pin_source: str = "ring") -> StreamHandle:
        # shed placements mint a real trace id (the caller's handle and
        # the journey journal must agree on one — a None id would drop
        # the edge rejection out of the journey path entirely)
        trace_id = new_trace_id()
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=None, deadline_s=None,
                      trace_id=trace_id, tenant=tenant)
        handle = StreamHandle(req, self, tenant=tenant,
                              priority=priority, slo_ttft_s=slo_ttft_s,
                              submit_t=t0, trace_id=trace_id)
        handle._resolve("rejected",
                        reject_reason=REJECT_POD_OVERLOADED)
        telemetry.count("fleet/pod_shed")
        with self._lock:
            self.n_shed += 1
            self._placements.append({
                "trace_id": trace_id, "uid": handle.uid, "t": t0,
                "dur_s": self._clock() - t0, "pod": None,
                "shed": True, "shed_reason": REJECT_POD_OVERLOADED,
                "tried": list(tried), "ring_key": ring_key,
                "pin": pin_source})
        return handle

    def cancel(self, handle: StreamHandle) -> None:
        """Edge-rejected handles name the root as their frontend; they
        are already terminal, so cancel is a no-op."""

    # ------------------------------------------------------ pod failover
    def _adopt_foreign(self, handle: StreamHandle, *, src_pod: str,
                       src_rid: Any = None,
                       exc: Optional[BaseException] = None) -> bool:
        """Cross-pod crash/drain failover: a pod with no routable
        survivor hands its salvaged handles here; the root re-homes
        each on the ring's next live pod (prefix-ordered, so replays
        land where the prompt's twins live)."""
        prompt = handle._request.prompt
        key = PrefixCache.key_for(prompt)
        n_emitted = len(handle.tokens)
        for pod_id in self._ring.pods_for(key, max(1, len(self.pods))):
            if pod_id == src_pod:
                continue
            leaf = self._placeable(pod_id)
            if leaf is None:
                continue
            target = leaf._place(prompt)
            src = f"{src_pod}/{src_rid}" if src_rid is not None \
                else src_pod
            if target.routable and target.frontend.adopt(
                    handle, rerouted_from=src):
                telemetry.count("fleet/pod_failover")
                telemetry.count("fleet/rerouted")
                if n_emitted:
                    telemetry.count("fleet/replayed")
                telemetry.instant(
                    "fleet/reroute", trace_id=handle.trace_id,
                    rerouted_from=src,
                    rerouted_to=f"{pod_id}/{target.rid}",
                    replayed_tokens=n_emitted)
                with self._lock:
                    self.n_pod_failover += 1
                    self._reroutes.append({
                        "trace_id": handle.trace_id,
                        "uid": handle.uid, "t": self._clock(),
                        "from_pod": src_pod, "from_replica": src,
                        "to_pod": pod_id,
                        "to_replica": f"{pod_id}/{target.rid}",
                        "replayed_tokens": n_emitted})
                logger.info(f"fleet pod failover: uid={handle.uid} "
                            f"{src} -> {pod_id}/{target.rid}")
                return True
        return False

    # --------------------------------------------------------- migration
    def _find_source(self, leaf: LeafRouter, uid: int,
                     src_rid: Optional[int]) -> FleetReplica:
        if src_rid is not None:
            return leaf._resolve_replica(src_rid)
        for rep in leaf.replicas:
            if not rep.alive:
                continue
            try:
                if int(uid) in rep.frontend.migration_candidates():
                    return rep
            except Exception:  # noqa: BLE001 — probe is best-effort
                continue
        raise MigrationError(
            f"uid {uid} not migratable from pod {leaf.pod_id}")

    def migrate(self, uid: int, src_pod: str, dst_pod: str, *,
                src_rid: Optional[int] = None,
                dst_rid: Optional[int] = None) -> bool:
        """Cross-pod live migration: export the running request from
        the source pod's replica and import it into the destination
        pod's, same non-lossy semantics as ``FleetRouter.migrate`` —
        a destination failure restores the request at the source."""
        sleaf = self.pods.get(str(src_pod))
        dleaf = self.pods.get(str(dst_pod))
        if sleaf is None or dleaf is None:
            raise MigrationError(
                f"unknown pod in {src_pod!r} -> {dst_pod!r}")
        t0 = self._clock()
        try:
            src = self._find_source(sleaf, uid, src_rid)
        except MigrationError as e:
            self._record_cross_failure(uid, src_pod, dst_pod, str(e))
            return False
        if dst_rid is not None:
            dst = dleaf._resolve_replica(dst_rid)
        else:
            routable = [r for r in dleaf.replicas if r.routable]
            if not routable:
                self._record_cross_failure(uid, src_pod, dst_pod,
                                           "no routable destination")
                return False
            dst = min(routable, key=dleaf._load_score)
        try:
            bundle, handle = src.frontend.migrate_out(uid)
        except MigrationError as e:
            self._record_cross_failure(uid, src_pod, dst_pod,
                                       f"export: {e}")
            return False
        resumed = len(bundle["tokens"])
        try:
            dst.frontend.migrate_in(
                bundle, handle, migrated_from=f"{src_pod}/{src.rid}")
        except MigrationError as e:
            try:
                src.frontend.migrate_in(bundle, handle,
                                        migrated_from=None)
            except MigrationError as e2:
                handle._resolve(
                    "error",
                    error=f"cross-pod migration failed both ways "
                          f"(dst: {e}; src restore: {e2})")
            self._record_cross_failure(uid, src_pod, dst_pod,
                                       f"import: {e}",
                                       trace_id=handle.trace_id)
            return False
        kv_bytes = int(bundle.get("kv_bytes", 0))
        telemetry.count("fleet/cross_pod_migrated")
        telemetry.count("fleet/cross_pod_migrate_bytes",
                        float(kv_bytes))
        telemetry.instant("fleet/migration", trace_id=handle.trace_id,
                          from_replica=f"{src_pod}/{src.rid}",
                          to_replica=f"{dst_pod}/{dst.rid}",
                          resumed_tokens=resumed, kv_bytes=kv_bytes)
        with self._lock:
            self.n_cross_migrated += 1
            self.cross_migrate_bytes += kv_bytes
            self._migrations.append({
                "trace_id": handle.trace_id, "uid": int(uid), "t": t0,
                "dur_s": self._clock() - t0,
                "from_pod": src_pod,
                "from_replica": f"{src_pod}/{src.rid}",
                "to_pod": dst_pod,
                "to_replica": f"{dst_pod}/{dst.rid}",
                "resumed_tokens": resumed, "kv_bytes": kv_bytes})
        logger.info(f"fleet cross-pod migration: uid={uid} "
                    f"{src_pod}/{src.rid} -> {dst_pod}/{dst.rid} "
                    f"({resumed} tokens resumed)")
        return True

    def _record_cross_failure(self, uid: int, src_pod: str,
                              dst_pod: str, why: str, *,
                              trace_id: Optional[str] = None) -> None:
        """``trace_id`` propagates from the in-flight handle whenever
        the failure happens after export (the handle exists and carries
        the request's id); pre-export failures have no handle, so the
        record keeps a None id rather than minting a fake one."""
        telemetry.count("fleet/cross_pod_migrate_failed")
        with self._lock:
            self.n_cross_migrate_failed += 1
            self._migrations.append({
                "trace_id": trace_id, "uid": int(uid),
                "t": self._clock(),
                "from_pod": src_pod, "to_pod": dst_pod, "failed": why})
        logger.warning(f"fleet cross-pod migration uid={uid} "
                       f"{src_pod}->{dst_pod} failed: {why}")

    def rebalance(self, *, spread_ratio: float = 2.0,
                  max_moves: int = 1) -> List[Dict[str, Any]]:
        """One cross-pod balancing pass: while the hottest placeable
        pod's drain estimate is at least ``spread_ratio`` times the
        coldest's, move one movable request hot -> cold (up to
        ``max_moves``). Per-pod spread stays the leaf's own
        ``rebalance``; this pass only levels across pods."""
        moves: List[Dict[str, Any]] = []
        for _ in range(max(0, int(max_moves))):
            cands: List[Tuple[str, LeafRouter, Dict[str, Any]]] = []
            for pod_id in sorted(self.pods):
                leaf = self._placeable(pod_id)
                if leaf is None:
                    continue
                snap = leaf.pod_snapshot(max_age_s=0.0)
                if snap["routable"]:
                    cands.append((pod_id, leaf, snap))
            if len(cands) < 2:
                break
            hot = max(cands, key=lambda c: c[2]["drain_s"])
            cold = min(cands, key=lambda c: c[2]["drain_s"])
            hot_drain = float(hot[2]["drain_s"])
            cold_drain = float(cold[2]["drain_s"])
            if hot_drain <= 0 \
                    or hot_drain < spread_ratio * max(cold_drain, 1e-9):
                break
            uid = None
            for rep in sorted(
                    (r for r in hot[1].replicas if r.alive),
                    key=hot[1]._load_score, reverse=True):
                try:
                    movable = rep.frontend.migration_candidates()
                except Exception:  # noqa: BLE001 — probe is best-effort
                    continue
                if movable:
                    uid = movable[0]
                    break
            if uid is None:
                break
            ok = self.migrate(uid, hot[0], cold[0])
            moves.append({"uid": int(uid), "from_pod": hot[0],
                          "to_pod": cold[0], "ok": ok,
                          "hot_drain_s": hot_drain,
                          "cold_drain_s": cold_drain})
            if not ok:
                break
        return moves

    # ----------------------------------------------------------- queries
    @property
    def n_pods(self) -> int:
        return len([p for p in self.pods
                    if p not in self._lost and p not in self._retiring])

    @property
    def n_replicas(self) -> int:
        return sum(len(leaf.replicas) for leaf in self.pods.values())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "pods": len(self.pods),
                "pods_placeable": self.n_pods,
                "pods_lost": sorted(self._lost),
                "pods_retiring": sorted(self._retiring),
                "routed": self.n_routed,
                "shed": self.n_shed,
                "spilled": self.n_spilled,
                "pod_failover": self.n_pod_failover,
                "cross_migrated": self.n_cross_migrated,
                "cross_migrate_failed": self.n_cross_migrate_failed,
                "cross_migrate_bytes": self.cross_migrate_bytes,
                "pods_lost_total": self.n_pods_lost,
                "pods_retired_total": self.n_pods_retired,
            }
        out["per_pod"] = {pod_id: leaf.stats()
                          for pod_id, leaf in self.pods.items()}
        return out

    # ---------------------------------------------------- observability
    def serve_metrics(self, *, host: str = "127.0.0.1", port: int = 0,
                      ttl_s: float = 2.0, namespace: str = "dstpu",
                      slo: bool = True,
                      slo_windows_s: Sequence[float] = (5.0, 60.0)):
        """Stand up the fleet observability plane: a
        :class:`~deepspeed_tpu.telemetry.fleetobs
        .FleetMetricsAggregator` over every pod (local frontends render
        directly, remotes scrape over ``GET /v1/metrics``) behind a
        :class:`~deepspeed_tpu.telemetry.exposition.MetricsServer`
        serving ``/fleet/metrics`` + ``/fleet/pods`` (and the root
        process's own ``/metrics`` / ``/readyz``). With ``slo``, each
        pod gets one :class:`~deepspeed_tpu.telemetry.slo.SLOEngine`
        attached to its local replicas' TraceLogs; per-pod burn feeds
        ``fleet/pod_burn_rate|pod=<p>`` gauges and the pod-level
        anomaly detector, whose tripped state degrades the root's
        ``/readyz``. Returns the server; the root owns it (``close()``
        stops it). Idempotent — a second call returns the first
        server."""
        if self._metrics_server is not None:
            return self._metrics_server
        from ...telemetry import core as _tcore
        from ...telemetry.exposition import MetricsServer
        from ...telemetry.fleetobs import FleetMetricsAggregator
        from ..frontend.health import HealthMonitor
        agg = FleetMetricsAggregator(self, ttl_s=ttl_s,
                                     namespace=namespace,
                                     clock=self._clock)
        if slo:
            from ...telemetry.slo import SLOEngine
            for pod_id, leaf in self.pods.items():
                engine = SLOEngine(windows_s=slo_windows_s,
                                   clock=self._clock)
                attached = 0
                for rep in leaf.replicas:
                    tracing = getattr(rep.frontend, "tracing", None)
                    if tracing is not None \
                            and hasattr(tracing, "add_listener"):
                        engine.attach(tracing)
                        attached += 1
                if attached:
                    agg.attach_slo(pod_id, engine)
        health = HealthMonitor(
            anomaly=agg.anomaly,
            checks={"pods_placeable": lambda: self.n_pods > 0})
        self._fleet_agg = agg
        self._metrics_server = MetricsServer(
            runtime=_tcore.get_runtime(), health=health, fleet=agg,
            host=host, port=port, namespace=namespace)
        logger.info("fleet observability plane serving on "
                    f"{self._metrics_server.url}/fleet/metrics")
        return self._metrics_server

    @property
    def fleet_aggregator(self):
        return self._fleet_agg

    def journey_journal(self) -> Dict[str, Any]:
        """Flat-router-shaped journal with pod-qualified replica ids
        (``<pod>/<rid>``): root placements/failovers/migrations merge
        with every leaf's own records, so the existing journey renderer
        draws pod hops without a schema change."""
        with self._lock:
            journal: Dict[str, Any] = {
                "placements": [dict(p) for p in self._placements],
                "reroutes": [dict(r) for r in self._reroutes],
                "migrations": [dict(m) for m in self._migrations],
                "crashes": [],
            }
        journal["replicas"] = {}
        for pod_id, leaf in self.pods.items():
            sub = leaf.journey_journal()
            for rec in sub["placements"]:
                rec = dict(rec)
                rec["pod"] = pod_id
                rec["replica"] = f"{pod_id}/{rec['replica']}"
                journal["placements"].append(rec)
            for name in ("reroutes", "crashes", "migrations"):
                for rec in sub[name]:
                    rec = dict(rec)
                    rec["pod"] = pod_id
                    for k in ("replica", "from_replica", "to_replica"):
                        if rec.get(k) is not None \
                                and "/" not in str(rec[k]):
                            rec[k] = f"{pod_id}/{rec[k]}"
                    journal[name].append(rec)
            for rid, trace in sub["replicas"].items():
                # the records INSIDE a leaf's TraceLog reference other
                # replicas by flat rid (within-pod crash salvage sets
                # rerouted_from="0") — qualify those too, or the
                # journey validator cannot follow the reroute chain
                # across the pod boundary
                trace = dict(trace)
                for key in ("requests", "live"):
                    recs = []
                    for rec in trace.get(key, ()):
                        rec = dict(rec)
                        for k in ("rerouted_from", "migrated_from"):
                            v = rec.get(k)
                            if v is not None and "/" not in str(v):
                                rec[k] = f"{pod_id}/{v}"
                        recs.append(rec)
                    trace[key] = recs
                journal["replicas"][f"{pod_id}/{rid}"] = trace
        return journal

    def tenants_report(self) -> Dict[str, Any]:
        """Fleet-wide per-tenant goodput merged across every pod."""
        merged: Dict[str, Dict[str, Any]] = {}
        per_pod: Dict[str, Any] = {}
        for pod_id, leaf in self.pods.items():
            rep = leaf.tenants_report()
            per_pod[pod_id] = rep
            for tenant, t in rep.get("tenants", {}).items():
                m = merged.setdefault(tenant, {
                    "n_requests": 0, "total_tokens": 0,
                    "goodput_tokens": 0})
                m["n_requests"] += t.get("n_requests", 0)
                m["total_tokens"] += t.get("total_tokens", 0)
                m["goodput_tokens"] += t.get("goodput_tokens", 0)
        for m in merged.values():
            m["goodput_fraction"] = (
                m["goodput_tokens"] / m["total_tokens"]
                if m["total_tokens"] else 1.0)
        return {"schema": "dstpu-hierarchy-tenants-v1",
                "n_tenants": len(merged), "tenants": merged,
                "per_pod": per_pod}

    def export_chrome(self, path: Optional[str] = None,
                      runtime=None) -> Dict[str, Any]:
        """One Perfetto file for the whole hierarchy: the shared
        runtime (pid 1), every replica's per-request lanes (pid 2),
        journey lanes (pid 3), and the pod lane (pid 5) — root
        placement decisions (ring key, pin source, spill/shed) as pod
        spans with cross-pod failover/migration flow arrows. Writes to
        ``path`` when given; always returns the trace object."""
        from ...telemetry import (chrome_trace, request_trace_events,
                                  write_chrome_trace)
        from ...telemetry import core as _tcore
        from ...telemetry.journey import (journey_trace_events,
                                          pod_lane_events)
        rt = runtime if runtime is not None else _tcore.get_runtime()
        journal = self.journey_journal()
        extra: List[dict] = []
        for rid in sorted(journal["replicas"]):
            extra.extend(request_trace_events(journal["replicas"][rid]))
        extra.extend(journey_trace_events(journal))
        extra.extend(pod_lane_events(journal))
        if path is None:
            return chrome_trace(rt, extra_events=extra)
        return write_chrome_trace(path, rt, extra_events=extra)

    def close(self, timeout: Optional[float] = None) -> None:
        if self._metrics_server is not None:
            try:
                self._metrics_server.stop()
            finally:
                self._metrics_server = None
                self._fleet_agg = None
        for ctrl in self.controllers.values():
            ctrl.stop()
        for leaf in self.pods.values():
            leaf.close(timeout)

    def __enter__(self) -> "RootRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
