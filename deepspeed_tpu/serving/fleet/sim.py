"""Deterministic discrete-event fleet simulator.

Validating 1000 replicas is impossible on real engines — and
``fleet_bench``'s simulated-engine pattern still burns real driver
threads and wall-clock sleeps, so it tops out around tens of replicas.
This module graduates that pattern into a first-class simulator that
drives the *real* control plane:

* :class:`SimClock` — virtual time. A heap of ``(t, seq, fn)`` events,
  no wall sleeps, no threads; ``run_until`` executes everything due and
  then pins the clock to the horizon (so self-rescheduling heartbeat /
  watchdog loops never prevent termination). The clock object is
  callable, so it drops straight into every ``clock=`` seam the serving
  stack already has (routers, TraceLog, admission, elastic
  controllers).
* :class:`SimReplica` — a replica satisfying the same surface
  ``FleetRouter`` drives (``submit`` / ``load_snapshot`` /
  ``holds_prefix`` / ``adopt`` / ``migrate_out`` / ``migrate_in`` /
  ``drain_pending`` / ``on_crash`` …) with configurable prefill/decode
  token rates. The real root/leaf routers, admission, elastic
  controllers, and migration paths run UNMODIFIED over it — the sim
  fakes the engine, never the control plane.
* Trace-driven workload generators (:func:`diurnal_trace`,
  :func:`tenant_skew_trace`, :func:`hot_prefix_storm`,
  :func:`multi_turn_trace`) and a :class:`ChaosInjector` (pod loss,
  slow and partitioned networks, zombie replicas that accept but never
  emit, clock-skewed heartbeats).

Tokens are deterministic — token ``k`` of a stream is
``prompt[-1]`` if ``k == 0`` else ``prompt[k % len(prompt)]``
(:func:`sim_expected`) — so a run can assert ZERO lost and ZERO
duplicated tokens through any chaos schedule by exact comparison, and
the same seed reproduces the same :class:`SimWorld` event log
byte-for-byte (the log never contains process-global ids or random
trace ids; handles get dense per-world ids).

Failure detection is the part chaos exists to exercise:
:class:`FleetWatchdog` judges liveness by heartbeat ARRIVAL time on
its own clock — never the replica's self-reported timestamp — so
clock-skewed replicas don't get false-killed, while partitioned
replicas (heartbeats dropped) and zombies (heartbeats fine, zero
token progress) both do get killed, which routes their streams through
the router's ordinary crash-salvage/replay path.

Host-side only — this module never imports JAX.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import itertools
import math
import random
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ...analysis import locks
from ...telemetry import core as telemetry
from ..engine import MIGRATE_SCHEMA, MigrationError
from ..frontend.admission import (PRIORITY_NORMAL, REJECT_FRONTEND_CLOSED,
                                  REJECT_FRONTEND_QUEUE_FULL)
from ..frontend.frontend import LOAD_SCHEMA, StreamHandle
from ..frontend.tracing import TraceLog
from ..paged_kv import PrefixCache
from ..scheduler import Request


def sim_expected(prompt: Sequence[int], n: int) -> List[int]:
    """The deterministic token oracle: what a correct end-to-end run
    delivers for ``prompt``'s first ``n`` tokens. Depends only on the
    ORIGINAL prompt and the emission position, so replay-after-crash
    and migration resume produce the identical continuation."""
    prompt = [int(t) for t in prompt]
    return [prompt[-1] if k == 0 else prompt[k % len(prompt)]
            for k in range(n)]


class SimClock:
    """Virtual time: an event heap and nothing else.

    Callable (returns ``now``) so it plugs into every ``clock=`` seam.
    ``run_until`` pops events in ``(t, seq)`` order — seq breaks ties
    by scheduling order, so a run is deterministic — and finally sets
    ``now`` to the horizon even when self-rescheduling loops (heart-
    beats, watchdog polls) still have future events queued."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._heap: List[tuple] = []
        self._seq = itertools.count()

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def call_at(self, t: float, fn: Callable, *args) -> None:
        heapq.heappush(self._heap,
                       (max(float(t), self._now), next(self._seq),
                        fn, args))

    def call_later(self, dt: float, fn: Callable, *args) -> None:
        self.call_at(self._now + float(dt), fn, *args)

    def run_until(self, t_end: float) -> int:
        """Execute every event due at or before ``t_end``; returns the
        number executed. The clock ends AT ``t_end``."""
        n = 0
        while self._heap and self._heap[0][0] <= t_end:
            t, _, fn, args = heapq.heappop(self._heap)
            self._now = t
            fn(*args)
            n += 1
        self._now = float(t_end)
        return n

    def run_for(self, dt: float) -> int:
        return self.run_until(self._now + float(dt))

    @property
    def pending_events(self) -> int:
        return len(self._heap)


class SimWorld:
    """One simulation run: the clock, the seeded RNG every random
    choice must come from, and the deterministic event log.

    The log is the byte-for-byte reproducibility artifact: entries are
    ``t=<6dp> <kind> k=v ...`` with sorted keys, and handles are named
    by DENSE per-world ids (assigned in first-sight order) — never by
    ``Request.uid`` (a process-global counter) or ``trace_id``
    (random), which would differ between two runs in one process."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.clock = SimClock()
        self.rng = random.Random(self.seed)
        self._events: List[str] = []
        self._records: List[tuple] = []    # (t, kind, kv) — same feed
        self._sids: Dict[int, int] = {}

    def sid(self, handle: StreamHandle) -> int:
        """Dense, run-stable id for one stream handle."""
        uid = handle.uid
        if uid not in self._sids:
            self._sids[uid] = len(self._sids)
        return self._sids[uid]

    def log(self, kind: str, **kv) -> None:
        parts = [f"t={self.clock.now():.6f}", kind]
        parts += [f"{k}={kv[k]}" for k in sorted(kv)]
        self._events.append(" ".join(parts))
        # the same single funnel also feeds the structured record list
        # behind sim_trace_events — the string log (and its digest)
        # stays byte-identical
        self._records.append((self.clock.now(), kind, dict(kv)))

    def records(self) -> List[tuple]:
        """The structured ``(t, kind, kv)`` mirror of the event log —
        what :func:`sim_trace_events` renders on virtual clocks."""
        return list(self._records)

    def event_log(self) -> str:
        return "\n".join(self._events) + ("\n" if self._events else "")

    def digest(self) -> str:
        return hashlib.sha256(
            self.event_log().encode("utf-8")).hexdigest()


@dataclasses.dataclass
class SimReplicaConfig:
    """One sim replica's performance envelope (token rates are the
    knobs the chaos legs scale with ``slow_factor``)."""
    prefill_tokens_per_s: float = 8192.0
    decode_tokens_per_s: float = 512.0
    chunk_s: float = 0.05            # decode chunk cadence
    max_running: int = 8             # concurrent decode lanes
    max_queue: int = 64              # waiting beyond the running set
    prefix_capacity: int = 256       # LRU prefix-cache entries
    heartbeat_every_s: float = 0.5


@dataclasses.dataclass
class _Lane:
    """One running request inside a sim replica."""
    handle: StreamHandle
    remaining: int
    ready_t: float                   # prefill completes at this time
    buffered: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False


class SimReplica:
    """A fleet replica with a synthetic engine behind the REAL frontend
    surface. Joins a router via ``add_remote`` (it walks and quacks
    like a :class:`~.remote.RemoteReplica`), so placement, crash
    salvage, draining, adoption/replay, and live migration all exercise
    the production code paths.

    Modes: ``ok`` (normal), ``zombie`` (accepts everything, emits
    nothing — heartbeats keep arriving), ``partitioned`` (keeps
    computing but its emissions and heartbeats never reach anyone;
    ``heal()`` flushes the buffered tokens IF it still owns the stream,
    a kill drops them — either way zero duplicates), ``dead``
    (crashed: in-flight work was salvaged through ``on_crash``),
    ``closed`` (gracefully retired). ``skew_s`` offsets the timestamps
    the replica self-reports in heartbeats — arrival-time watchdogs
    must not care."""

    def __init__(self, label: str, world: SimWorld,
                 config: Optional[SimReplicaConfig] = None):
        self.label = str(label)
        self.world = world
        self.clock = world.clock
        self.cfg = config or SimReplicaConfig()
        self.mode = "ok"
        self.skew_s = 0.0
        self.slow_factor = 1.0
        self.draining = False
        self.on_crash = None
        self.postmortem_path: Optional[str] = None
        self.tracing = TraceLog(clock=self.clock)
        self.n_submitted = 0
        self.n_emitted = 0
        self.last_progress_t = self.clock.now()
        self._lock = locks.make_lock("fleet.sim_replica")
        self._lanes: Dict[int, _Lane] = {}     # uid -> lane, FIFO order
        self._waiting: List[StreamHandle] = []
        self._prefixes: Dict[bytes, None] = {}  # insertion-ordered LRU
        self._chunk_pending = False
        self._watchdog: Optional["FleetWatchdog"] = None
        self._hb_started = False

    # ------------------------------------------------------ sim plumbing
    def _rate(self, tokens_per_s: float) -> float:
        return tokens_per_s / max(self.slow_factor, 1e-9)

    def _owns(self, handle: StreamHandle) -> bool:
        return handle._frontend is self and not handle.done

    def _remember_prefix(self, prompt) -> None:
        key = PrefixCache.key_for(prompt)
        self._prefixes.pop(key, None)
        self._prefixes[key] = None
        while len(self._prefixes) > self.cfg.prefix_capacity:
            self._prefixes.pop(next(iter(self._prefixes)))

    def _start_lane(self, handle: StreamHandle) -> None:
        n_emitted = len(handle.tokens)
        prefill_tokens = int(handle._prompt.shape[0]) + n_emitted
        ready_t = self.clock.now() + prefill_tokens / self._rate(
            self.cfg.prefill_tokens_per_s)
        self._lanes[handle.uid] = _Lane(
            handle=handle,
            remaining=handle._max_new_tokens - n_emitted,
            ready_t=ready_t)
        self._remember_prefix(handle._prompt)
        self._kick()

    def _kick(self) -> None:
        if self._chunk_pending or self.mode in ("dead", "closed"):
            return
        if not self._lanes and not self._waiting:
            return
        self._chunk_pending = True
        self.clock.call_later(self.cfg.chunk_s, self._chunk)

    def _chunk(self) -> None:
        self._chunk_pending = False
        if self.mode in ("dead", "closed"):
            return
        while self._waiting and len(self._lanes) < self.cfg.max_running:
            handle = self._waiting.pop(0)
            if handle.done or not self._owns(handle):
                continue
            self._start_lane(handle)
        if self.mode != "zombie":
            budget = max(1, int(round(
                self._rate(self.cfg.decode_tokens_per_s)
                * self.cfg.chunk_s)))
            now = self.clock.now()
            progressed = False
            while budget > 0:
                ready = [ln for ln in self._lanes.values()
                         if ln.ready_t <= now and ln.remaining > 0
                         and not ln.finished]
                if not ready:
                    break
                for lane in ready:          # round-robin, FIFO order
                    if budget <= 0:
                        break
                    self._emit_one(lane)
                    budget -= 1
                    progressed = True
            if progressed:
                self.last_progress_t = now
            for uid in [u for u, ln in self._lanes.items()
                        if ln.finished and ln.handle.done]:
                del self._lanes[uid]
        self._kick()

    def _emit_one(self, lane: _Lane) -> None:
        handle = lane.handle
        if not self._owns(handle):
            # the router re-homed this stream (watchdog kill raced a
            # heal): stop computing for it, and above all never push
            self._lanes.pop(handle.uid, None)
            return
        pos = len(handle.tokens) + len(lane.buffered)
        tok = sim_expected(handle._prompt, pos + 1)[pos]
        lane.remaining -= 1
        eos = handle._request.eos_token_id
        if eos is not None and tok == eos:
            lane.remaining = 0
        if self.mode == "partitioned":
            lane.buffered.append(tok)
            if lane.remaining <= 0:
                lane.finished = True
            return
        handle._push([tok])
        self.tracing.chunk(handle.uid, 1)
        self.n_emitted += 1
        if lane.remaining <= 0:
            lane.finished = True
            self._finish_lane(lane)

    def _finish_lane(self, lane: _Lane) -> None:
        handle = lane.handle
        self.tracing.finish(handle.uid, "done")
        handle._resolve("done")
        self._lanes.pop(handle.uid, None)
        self.world.log("finish", replica=self.label,
                       sid=self.world.sid(handle),
                       n_tokens=len(handle.tokens))

    # --------------------------------------------------- chaos controls
    def fail(self, exc: Optional[BaseException] = None) -> None:
        """Abrupt crash: every in-flight stream is salvaged through
        ``on_crash`` (the router's reroute/replay path) exactly like a
        dead driver thread; partition-era buffered tokens are dropped
        un-pushed, so the survivor's replay cannot duplicate."""
        if self.mode in ("dead", "closed"):
            return
        exc = exc or RuntimeError("sim replica failed")
        salvaged = []
        for lane in self._lanes.values():
            if not lane.handle.done:
                salvaged.append(lane.handle)
        for handle in self._waiting:
            if not handle.done:
                salvaged.append(handle)
        self._lanes.clear()
        self._waiting.clear()
        self.mode = "dead"
        self.world.log("crash", replica=self.label,
                       n_salvaged=len(salvaged))
        if self.on_crash is not None:
            self.on_crash(self, salvaged, exc)
        else:
            for handle in salvaged:
                handle._resolve("error", error=str(exc))

    def set_zombie(self) -> None:
        if self.mode == "ok":
            self.mode = "zombie"
            self.world.log("zombie", replica=self.label)

    def set_partitioned(self) -> None:
        if self.mode == "ok":
            self.mode = "partitioned"
            self.world.log("partition", replica=self.label)

    def heal(self) -> None:
        """End a partition. Buffered emissions flush to their handles
        IF this replica still owns them — a stream the watchdog
        already re-homed keeps its new home and the stale buffer drops
        on the floor (zero duplicates either way)."""
        if self.mode != "partitioned":
            return
        self.mode = "ok"
        self.world.log("heal", replica=self.label)
        for uid, lane in list(self._lanes.items()):
            handle = lane.handle
            if not self._owns(handle):
                self._lanes.pop(uid, None)
                continue
            if lane.buffered:
                handle._push(lane.buffered)
                self.tracing.chunk(handle.uid, len(lane.buffered))
                self.n_emitted += len(lane.buffered)
                lane.buffered = []
                self.last_progress_t = self.clock.now()
            if lane.finished:
                self._finish_lane(lane)
        self._kick()

    def set_slow(self, factor: float) -> None:
        self.slow_factor = max(float(factor), 1e-9)
        self.world.log("slow", replica=self.label,
                       factor=f"{self.slow_factor:g}")

    def set_skew(self, offset_s: float) -> None:
        self.skew_s = float(offset_s)
        self.world.log("skew", replica=self.label,
                       offset=f"{self.skew_s:g}")

    # ----------------------------------------------------- heartbeats
    def attach_watchdog(self, watchdog: "FleetWatchdog") -> None:
        self._watchdog = watchdog
        watchdog.register(self)
        if not self._hb_started:
            self._hb_started = True
            self.clock.call_later(self.cfg.heartbeat_every_s,
                                  self._heartbeat)

    def _heartbeat(self) -> None:
        if self.mode in ("dead", "closed"):
            return
        if self.mode != "partitioned" and self._watchdog is not None:
            # the SELF-REPORTED timestamp carries the skew; arrival
            # time (the watchdog's own clock) does not
            self._watchdog.beat(self,
                                self_t=self.clock.now() + self.skew_s)
        self.clock.call_later(self.cfg.heartbeat_every_s,
                              self._heartbeat)

    # ------------------------------------------------ frontend surface
    @property
    def driver_alive(self) -> bool:
        return self.mode not in ("dead", "closed")

    @property
    def crashed(self) -> bool:
        return self.mode == "dead"

    def has_work(self) -> bool:
        return bool(self._lanes or self._waiting)

    def submit(self, prompt, *, priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               trace_id: Optional[str] = None) -> StreamHandle:
        now = self.clock.now()
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id,
                      deadline_s=(now + deadline_s)
                      if deadline_s is not None else None,
                      trace_id=trace_id, tenant=tenant)
        handle = StreamHandle(req, self, tenant=tenant,
                              priority=priority, slo_ttft_s=slo_ttft_s,
                              submit_t=now, trace_id=trace_id)
        self.n_submitted += 1
        if not self.driver_alive:
            self.tracing.record_rejected(req.uid, REJECT_FRONTEND_CLOSED)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
            return handle
        if len(self._waiting) >= self.cfg.max_queue:
            self.tracing.record_rejected(req.uid,
                                         REJECT_FRONTEND_QUEUE_FULL)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_QUEUE_FULL)
            return handle
        self.tracing.start(req.uid, tenant=tenant, priority=priority,
                           prompt_len=req.prompt_len,
                           max_new_tokens=max_new_tokens,
                           slo_ttft_s=slo_ttft_s, trace_id=trace_id,
                           replica=self.label)
        self.tracing.mark(req.uid, "submitted", t=now)
        self.world.log("accept", replica=self.label,
                       sid=self.world.sid(handle), tenant=tenant)
        if len(self._lanes) < self.cfg.max_running:
            self._start_lane(handle)
        else:
            self._waiting.append(handle)
            self._kick()
        return handle

    def cancel(self, handle: StreamHandle) -> None:
        if handle.done or not self._owns(handle):
            return
        self._lanes.pop(handle.uid, None)
        self._waiting = [h for h in self._waiting
                         if h.uid != handle.uid]
        self.tracing.finish(handle.uid, "cancelled")
        handle._resolve("cancelled")

    def close(self, timeout: Optional[float] = None) -> None:
        if self.mode in ("dead", "closed"):
            return
        leftovers = self._waiting + [ln.handle
                                     for ln in self._lanes.values()]
        self._waiting = []
        self._lanes.clear()
        self.mode = "closed"
        for handle in leftovers:
            if not handle.done:
                self.tracing.record_rejected(handle.uid,
                                             REJECT_FRONTEND_CLOSED)
                handle._resolve("rejected",
                                reject_reason=REJECT_FRONTEND_CLOSED)

    def load_snapshot(self) -> Dict[str, Any]:
        backlog = sum(ln.remaining for ln in self._lanes.values())
        backlog += sum(h._max_new_tokens + int(h._prompt.shape[0])
                       for h in self._waiting)
        return {
            "schema": LOAD_SCHEMA,
            "admission": {"pending": len(self._waiting)},
            "throughput": {"tokens_per_s": self._rate(
                self.cfg.decode_tokens_per_s)},
            "engine_backlog_tokens": int(backlog),
            "engine_queue_depth": 0,
            "engine_running": len(self._lanes),
        }

    def holds_prefix(self, key: bytes) -> bool:
        return key in self._prefixes

    def migration_candidates(self) -> List[int]:
        now = self.clock.now()
        if self.mode != "ok":
            return []
        return [uid for uid, ln in self._lanes.items()
                if ln.ready_t <= now and ln.remaining > 0
                and len(ln.handle.tokens) > 0]

    def migrate_out(self, uid: int, timeout: Optional[float] = 30.0):
        if not self.driver_alive:
            raise MigrationError("sim replica is closed or dead")
        lane = self._lanes.get(int(uid))
        if lane is None or lane.handle.done or lane.buffered:
            raise MigrationError(f"uid {uid} is not migratable here")
        handle = lane.handle
        del self._lanes[int(uid)]
        self.tracing.finish(uid, "migrated")
        emitted = handle.tokens
        bundle = {
            "schema": MIGRATE_SCHEMA,
            "uid": int(uid),
            "trace_id": handle.trace_id,
            "prompt": [int(t) for t in handle._prompt],
            "tokens": [int(t) for t in emitted],
            "max_new_tokens": int(handle._max_new_tokens),
            "kv": {},
            "kv_bytes": 8 * (int(handle._prompt.shape[0])
                             + len(emitted)),
            "block_size": 1,
            "sampling": {"eos_token_id": handle._request.eos_token_id,
                         "tenant": handle.tenant,
                         "priority": int(handle.priority)},
        }
        self.world.log("migrate_out", replica=self.label,
                       sid=self.world.sid(handle))
        return bundle, handle

    def migrate_in(self, bundle: Dict[str, Any],
                   handle: Optional[StreamHandle] = None, *,
                   migrated_from: Optional[str] = None,
                   timeout: Optional[float] = 30.0) -> StreamHandle:
        if bundle.get("schema") != MIGRATE_SCHEMA:
            raise MigrationError(
                f"bad bundle schema {bundle.get('schema')!r}")
        if not self.driver_alive or self.mode != "ok":
            raise MigrationError("sim replica cannot host the request")
        if handle is None:
            raise MigrationError(
                "sim migrate_in needs the in-process handle")
        if len(self._lanes) >= self.cfg.max_running \
                and self.cfg.max_queue == 0:
            raise MigrationError("sim replica is full")
        handle._frontend = self
        uid = handle.uid
        # KV moved with the bundle: the lane resumes at the migrated
        # cursor with no replay prefill
        self._lanes[uid] = _Lane(
            handle=handle,
            remaining=handle._max_new_tokens - len(bundle["tokens"]),
            ready_t=self.clock.now())
        self._remember_prefix(handle._prompt)
        self.tracing.start(uid, tenant=handle.tenant,
                           priority=handle.priority,
                           trace_id=handle.trace_id,
                           replica=self.label,
                           migrated_from=migrated_from,
                           resumed_tokens=len(bundle["tokens"]))
        self.tracing.mark(uid, "submitted", t=handle.submit_t)
        self.world.log("migrate_in", replica=self.label,
                       sid=self.world.sid(handle))
        self._kick()
        return handle

    def drain_pending(self) -> List[StreamHandle]:
        out = []
        for handle in self._waiting:
            if handle.done:
                continue
            self.tracing.finish(handle.uid, "rerouted")
            out.append(handle)
        self._waiting = []
        return out

    def adopt(self, handle: StreamHandle,
              rerouted_from: Optional[str] = None) -> bool:
        if handle.done:
            return False
        emitted = handle.tokens
        n_emitted = len(emitted)
        eos = handle._request.eos_token_id
        if n_emitted >= handle._max_new_tokens or (
                eos is not None and n_emitted and emitted[-1] == eos):
            # already fully delivered — the crash only stole the status
            self.tracing.start(handle.uid, trace_id=handle.trace_id,
                               replica=self.label,
                               rerouted_from=rerouted_from)
            self.tracing.finish(handle.uid, "done")
            handle._resolve("done")
            return True
        if not self.driver_alive or self.draining:
            self.tracing.record_rejected(handle.uid,
                                         REJECT_FRONTEND_CLOSED)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
            return False
        if len(self._waiting) >= self.cfg.max_queue:
            self.tracing.record_rejected(handle.uid,
                                         REJECT_FRONTEND_QUEUE_FULL)
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_QUEUE_FULL)
            return False
        handle._frontend = self
        self.n_submitted += 1
        self.tracing.start(handle.uid, tenant=handle.tenant,
                           priority=handle.priority,
                           prompt_len=int(handle._prompt.shape[0]),
                           max_new_tokens=handle._max_new_tokens,
                           trace_id=handle.trace_id,
                           replica=self.label,
                           rerouted_from=rerouted_from,
                           replayed_tokens=n_emitted)
        self.tracing.mark(handle.uid, "submitted", t=handle.submit_t)
        self.world.log("adopt", replica=self.label,
                       sid=self.world.sid(handle),
                       replayed=n_emitted)
        if len(self._lanes) < self.cfg.max_running:
            self._start_lane(handle)   # replay re-prefills prompt+emitted
        else:
            self._waiting.append(handle)
            self._kick()
        return True

    def stats(self) -> Dict[str, Any]:
        return {
            "submitted": self.n_submitted,
            "emitted": self.n_emitted,
            "pending_admission": len(self._waiting),
            "running": len(self._lanes),
            "mode": self.mode,
            "terminal": dict(self.tracing.counters),
        }


class FleetWatchdog:
    """Arrival-time failure detector for sim fleets.

    Two independent triggers, matching the two ways a replica lies:

    * **heartbeat silence** — no heartbeat ARRIVED for
      ``heartbeat_timeout_s`` (partitioned or crashed-without-hook).
      Arrival time is read off the watchdog's own clock; the replica's
      self-reported timestamp is recorded but never judged, so a
      clock-skewed replica is NOT false-killed.
    * **zero progress** — heartbeats keep arriving but a replica with
      queued/running work emitted nothing for ``progress_timeout_s``
      (the zombie case: accepts everything, emits nothing).

    A kill calls ``SimReplica.fail``, which salvages every in-flight
    stream through the router's ordinary ``on_crash`` reroute path —
    detection is the only thing the watchdog adds."""

    def __init__(self, world: SimWorld, *,
                 heartbeat_timeout_s: float = 2.0,
                 progress_timeout_s: float = 5.0,
                 poll_every_s: float = 0.5):
        self.world = world
        self.clock = world.clock
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.progress_timeout_s = float(progress_timeout_s)
        self.poll_every_s = float(poll_every_s)
        self.n_killed = 0
        self._lock = locks.make_lock("fleet.sim_watchdog")
        self._last_arrival: Dict[int, float] = {}
        self._last_self_t: Dict[int, float] = {}
        self._work_since: Dict[int, float] = {}
        self._replicas: Dict[int, SimReplica] = {}
        self._started = False

    def register(self, replica: SimReplica) -> None:
        self._replicas[id(replica)] = replica
        self._last_arrival[id(replica)] = self.clock.now()

    def beat(self, replica: SimReplica, *, self_t: float) -> None:
        self._last_arrival[id(replica)] = self.clock.now()
        self._last_self_t[id(replica)] = float(self_t)

    def start(self) -> None:
        if not self._started:
            self._started = True
            self.clock.call_later(self.poll_every_s, self._poll)

    def _poll(self) -> None:
        now = self.clock.now()
        for key, rep in list(self._replicas.items()):
            if rep.mode in ("dead", "closed"):
                continue
            silent_s = now - self._last_arrival.get(key, now)
            if silent_s > self.heartbeat_timeout_s:
                self._kill(rep, f"no heartbeat for {silent_s:.1f}s")
                continue
            # zero-progress is judged only over a span the replica has
            # CONTINUOUSLY held work: an idle replica's progress stamp
            # goes stale by construction, and a batch of streams
            # adopted from a fresh kill must not read as a zombie in
            # the very poll pass that re-homed them (cascade kill)
            if not rep.has_work():
                self._work_since.pop(key, None)
                continue
            worked_s = now - self._work_since.setdefault(key, now)
            if worked_s > self.progress_timeout_s and \
                    now - rep.last_progress_t > self.progress_timeout_s:
                self._kill(rep, "accepting but not emitting")
                self._work_since.pop(key, None)
        self.clock.call_later(self.poll_every_s, self._poll)

    def _kill(self, rep: SimReplica, why: str) -> None:
        with self._lock:
            self.n_killed += 1
        telemetry.count("fleet/sim_watchdog_kill")
        self.world.log("watchdog_kill", replica=rep.label, why=why)
        rep.fail(RuntimeError(f"watchdog: {why}"))


class ChaosInjector:
    """Scripted failure schedule against a hierarchical sim fleet.

    Every injection is an event on the world clock, so a chaos run is
    as deterministic as a clean one — same seed, same schedule, same
    event log. Counters land as ``fleet/sim_chaos_*``."""

    def __init__(self, world: SimWorld, root=None):
        self.world = world
        self.clock = world.clock
        self.root = root
        self.n_injected = 0

    def _fire(self, kind: str, fn: Callable, *args) -> None:
        self.n_injected += 1
        telemetry.count(f"fleet/sim_chaos_{kind}")
        fn(*args)

    def pod_loss(self, t: float, pod_id: str) -> None:
        """At ``t``: the whole pod drops off the ring and every replica
        in it crashes — streams re-home cross-pod through salvage."""
        self.clock.call_at(t, self._fire, "pod_loss",
                           self._pod_loss, pod_id)

    def _pod_loss(self, pod_id: str) -> None:
        self.world.log("chaos_pod_loss", pod=pod_id)
        leaf = self.root.pods.get(str(pod_id)) \
            if self.root is not None else None
        if leaf is None:
            return
        self.root.mark_pod_lost(pod_id)
        for rep in list(leaf.replicas):
            fail = getattr(rep.frontend, "fail", None)
            if fail is not None:
                fail(RuntimeError(f"pod {pod_id} lost"))

    def zombie(self, t: float, replica: SimReplica) -> None:
        self.clock.call_at(t, self._fire, "zombie", replica.set_zombie)

    def partition(self, t: float, replica: SimReplica,
                  heal_t: Optional[float] = None) -> None:
        self.clock.call_at(t, self._fire, "partition",
                           replica.set_partitioned)
        if heal_t is not None:
            self.clock.call_at(heal_t, replica.heal)

    def slow(self, t: float, replica: SimReplica, factor: float,
             until_t: Optional[float] = None) -> None:
        self.clock.call_at(t, self._fire, "slow",
                           replica.set_slow, factor)
        if until_t is not None:
            self.clock.call_at(until_t, replica.set_slow, 1.0)

    def skew(self, t: float, replica: SimReplica,
             offset_s: float) -> None:
        self.clock.call_at(t, self._fire, "skew",
                           replica.set_skew, offset_s)


# --------------------------------------------------------------------
# workload generators — pure functions of the world RNG, returning
# arrival records {"t", "prompt", "tenant", "max_new_tokens"} in time
# order, so a trace is reproducible from the seed alone
# --------------------------------------------------------------------

def _rand_prompt(rng: random.Random, n: int,
                 vocab: int = 997) -> List[int]:
    return [rng.randrange(1, vocab) for _ in range(max(1, n))]


def diurnal_trace(rng: random.Random, *, duration_s: float,
                  base_rps: float, peak_rps: float,
                  period_s: float = 60.0, prompt_len: int = 8,
                  max_new_tokens: int = 8,
                  tenant: str = "default") -> List[Dict[str, Any]]:
    """Sinusoidal arrival rate between ``base_rps`` (trough) and
    ``peak_rps`` (crest) with period ``period_s`` — the compressed
    day/night cycle an elastic policy must track."""
    out: List[Dict[str, Any]] = []
    t = 0.0
    while True:
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
        rate = base_rps + (peak_rps - base_rps) * phase
        t += rng.expovariate(max(rate, 1e-9))
        if t >= duration_s:
            return out
        out.append({"t": t, "prompt": _rand_prompt(rng, prompt_len),
                    "tenant": tenant,
                    "max_new_tokens": max_new_tokens})


def tenant_skew_trace(rng: random.Random, *, duration_s: float,
                      rps: float, tenants: Sequence[str],
                      skew: float = 1.5, prompt_len: int = 8,
                      max_new_tokens: int = 8) -> List[Dict[str, Any]]:
    """Zipf-weighted tenant mix: tenant ``i`` arrives with weight
    ``1/(i+1)**skew`` — one whale, a long tail."""
    weights = [1.0 / (i + 1) ** skew for i in range(len(tenants))]
    out: List[Dict[str, Any]] = []
    t = 0.0
    while True:
        t += rng.expovariate(max(rps, 1e-9))
        if t >= duration_s:
            return out
        tenant = rng.choices(list(tenants), weights=weights)[0]
        out.append({"t": t, "prompt": _rand_prompt(rng, prompt_len),
                    "tenant": tenant,
                    "max_new_tokens": max_new_tokens})


def hot_prefix_storm(rng: random.Random, *, duration_s: float,
                     rps: float, n_hot: int = 4,
                     hot_fraction: float = 0.8, prompt_len: int = 16,
                     max_new_tokens: int = 8,
                     tenant: str = "default") -> List[Dict[str, Any]]:
    """A small hot set of identical prompts dominating arrivals —
    the trace where prefix-affinity placement pays or doesn't. A
    consistent-hash root sends all repeats of one hot prompt to one
    pod, so the leaf's affinity probe finds the cache holder."""
    hot = [_rand_prompt(rng, prompt_len) for _ in range(max(1, n_hot))]
    out: List[Dict[str, Any]] = []
    t = 0.0
    while True:
        t += rng.expovariate(max(rps, 1e-9))
        if t >= duration_s:
            return out
        if rng.random() < hot_fraction:
            prompt = list(rng.choice(hot))
        else:
            prompt = _rand_prompt(rng, prompt_len)
        out.append({"t": t, "prompt": prompt, "tenant": tenant,
                    "max_new_tokens": max_new_tokens})


def multi_turn_trace(rng: random.Random, *, n_sessions: int,
                     turns: int = 3, think_s: float = 3.0,
                     start_spread_s: float = 5.0, first_len: int = 8,
                     user_len: int = 4,
                     max_new_tokens: int = 8) -> List[Dict[str, Any]]:
    """Conversations: each turn's prompt is the previous prompt plus
    the model's (deterministic) answer plus fresh user tokens, so later
    turns are growing-prefix repeats — the multi-turn arrival pattern
    that rewards prefix caching and stable placement."""
    out: List[Dict[str, Any]] = []
    for s in range(max(1, n_sessions)):
        t = rng.uniform(0.0, start_spread_s)
        prompt = _rand_prompt(rng, first_len)
        tenant = f"session-{s}"
        for _ in range(max(1, turns)):
            out.append({"t": t, "prompt": list(prompt),
                        "tenant": tenant,
                        "max_new_tokens": max_new_tokens})
            answer = sim_expected(prompt, max_new_tokens)
            prompt = prompt + answer + _rand_prompt(rng, user_len)
            t += think_s + rng.uniform(0.0, think_s)
    out.sort(key=lambda ev: ev["t"])
    return out


# --------------------------------------------------------------------
# drivers
# --------------------------------------------------------------------

def build_sim_fleet(world: SimWorld, root, *, n_pods: int,
                    pod_size: int,
                    config: Optional[SimReplicaConfig] = None,
                    watchdog: Optional[FleetWatchdog] = None,
                    pod_prefix: str = "pod") -> List[SimReplica]:
    """Populate ``root`` (a :class:`~.hierarchy.RootRouter`) with
    ``n_pods`` pods of ``pod_size`` sim replicas each; returns every
    replica created. With a ``watchdog``, replicas heartbeat into it."""
    replicas: List[SimReplica] = []
    for p in range(n_pods):
        pod_id = f"{pod_prefix}{p:03d}"
        pod = [SimReplica(f"{pod_id}.{i}", world, config)
               for i in range(pod_size)]
        root.add_pod(pod_id, remotes=pod)
        replicas.extend(pod)
    if watchdog is not None:
        for rep in replicas:
            rep.attach_watchdog(watchdog)
        watchdog.start()
    return replicas


def run_trace(world: SimWorld, router, trace: Sequence[Dict[str, Any]],
              *, horizon_s: float) -> List[tuple]:
    """Schedule every arrival on the world clock, run to ``horizon_s``,
    and return ``(event, handle)`` pairs in arrival order."""
    results: List[tuple] = []

    def _submit(ev: Dict[str, Any]) -> None:
        handle = router.submit(
            ev["prompt"], tenant=ev.get("tenant", "default"),
            max_new_tokens=ev.get("max_new_tokens", 8))
        results.append((ev, handle))

    for ev in trace:
        world.clock.call_at(ev["t"], _submit, ev)
    world.clock.run_until(horizon_s)
    return results


def verify_streams(results: Sequence[tuple]) -> Dict[str, int]:
    """Exact end-to-end audit against the token oracle. ``lost`` is a
    stream that terminated without its full output after partial
    delivery (or errored / never resolved); ``duplicated`` is any
    over-delivery or oracle mismatch; ``rejected`` only counts CLEAN
    rejections (zero tokens delivered — the caller was told up
    front)."""
    out = {"n": len(results), "done": 0, "rejected": 0, "lost": 0,
           "duplicated": 0, "pending": 0}
    for ev, handle in results:
        status = handle.status
        toks = handle.tokens
        want_n = ev.get("max_new_tokens", 8)
        if status == "done":
            want = sim_expected(ev["prompt"], want_n)
            if len(toks) > len(want) or toks != want[:len(toks)]:
                out["duplicated"] += 1
            elif len(toks) < len(want):
                out["lost"] += 1
            else:
                out["done"] += 1
        elif status == "rejected" and not toks:
            out["rejected"] += 1
        elif status == "pending":
            out["pending"] += 1
        else:
            out["lost"] += 1
    return out


def log_results(world: SimWorld, results: Sequence[tuple]) -> None:
    """Append every stream's terminal record to the world event log
    (arrival order — deterministic), closing the byte-reproducibility
    artifact."""
    for ev, handle in results:
        world.log("result", sid=world.sid(handle),
                  status=handle.status, n_tokens=len(handle.tokens))


# --------------------------------------------------------------------
# sim-time timeline export
# --------------------------------------------------------------------

#: pid of the sim timeline process in a Chrome trace — virtual clocks,
#: one lane per sim replica (pid 3 = journeys, pid 5 = fleet pods)
PID_SIM = 4

#: record kinds that render as instants on the emitting replica's lane
_SIM_INSTANTS = ("accept", "finish", "crash", "zombie", "partition",
                 "heal", "slow", "skew", "adopt")


def sim_trace_events(world: SimWorld, *,
                     pid: int = PID_SIM) -> List[dict]:
    """Render the world's structured event records as Chrome trace
    events on VIRTUAL time (``ts`` = sim seconds * 1e6): one lane per
    sim replica plus a world lane (tid 0) for chaos/watchdog/result
    records. Chaos pod losses are global-scope instants; a watchdog
    kill is a flow arrow from the world lane to the killed replica's
    lane; a migration draws an arrow from ``migrate_out`` to the
    matching ``migrate_in`` (paired by sid, in order). Deterministic —
    a function of the event log only, so two same-seed runs export the
    identical trace."""
    records = world.records()
    labels = sorted({str(kv["replica"]) for _, _, kv in records
                     if "replica" in kv})
    lane = {lbl: i for i, lbl in enumerate(labels, start=1)}
    events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"fleet sim (seed {world.seed})"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "world"}},
    ]
    for lbl in labels:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": lane[lbl], "args": {"name": lbl}})

    def us(t: float) -> float:
        return float(t) * 1e6

    out_pending: Dict[str, List[tuple]] = {}   # sid -> [(i, t, label)]
    n_hops = 0
    for i, (t, kind, kv) in enumerate(records):
        lbl = str(kv.get("replica", ""))
        tid = lane.get(lbl, 0)
        args = {k: v for k, v in kv.items()}
        if kind in _SIM_INSTANTS:
            events.append({"name": kind, "ph": "i", "s": "t",
                           "ts": us(t), "pid": pid, "tid": tid,
                           "args": args})
        elif kind == "chaos_pod_loss":
            events.append({"name": f"pod loss {kv.get('pod')}",
                           "ph": "i", "s": "g", "ts": us(t),
                           "pid": pid, "tid": 0, "args": args})
        elif kind == "watchdog_kill":
            common = {"name": "watchdog_kill", "cat": "watchdog",
                      "id": f"simkill:{i}", "pid": pid, "args": args}
            events.append({**common, "ph": "s", "tid": 0, "ts": us(t)})
            events.append({**common, "ph": "f", "bp": "e", "tid": tid,
                           "ts": us(t) + 1.0})
        elif kind == "migrate_out":
            out_pending.setdefault(str(kv.get("sid")), []).append(
                (i, t, lbl))
            events.append({"name": kind, "ph": "i", "s": "t",
                           "ts": us(t), "pid": pid, "tid": tid,
                           "args": args})
        elif kind == "migrate_in":
            events.append({"name": kind, "ph": "i", "s": "t",
                           "ts": us(t), "pid": pid, "tid": tid,
                           "args": args})
            pending = out_pending.get(str(kv.get("sid")))
            if pending:
                j, t0, src = pending.pop(0)
                n_hops += 1
                common = {"name": "sim_migrate", "cat": "sim_migrate",
                          "id": f"simmigrate:{j}", "pid": pid,
                          "args": {"sid": kv.get("sid"),
                                   "from": src, "to": lbl}}
                events.append({**common, "ph": "s",
                               "tid": lane.get(src, 0), "ts": us(t0)})
                events.append({**common, "ph": "f", "bp": "e",
                               "tid": tid,
                               "ts": max(us(t), us(t0) + 1.0)})
        elif kind == "result":
            events.append({"name": f"result:{kv.get('status')}",
                           "ph": "i", "s": "t", "ts": us(t),
                           "pid": pid, "tid": 0, "args": args})
        else:
            events.append({"name": kind, "ph": "i", "s": "t",
                           "ts": us(t), "pid": pid, "tid": tid,
                           "args": args})
    return events


def export_sim_trace(world: SimWorld,
                     path: Optional[str] = None) -> Dict[str, Any]:
    """One Perfetto file of the whole simulated fleet on virtual
    clocks. Writes to ``path`` when given; always returns the trace
    object (``bin/tputrace validate`` passes on it)."""
    from ...telemetry.export import chrome_trace, write_chrome_trace
    meta = {"source": "fleetsim", "seed": world.seed,
            "digest": world.digest()}
    evs = sim_trace_events(world)
    if path is None:
        return chrome_trace(None, extra_events=evs, metadata=meta)
    return write_chrome_trace(path, None, extra_events=evs,
                              metadata=meta)
