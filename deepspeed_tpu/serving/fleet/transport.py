"""ReplicaServer: the fleet's wire — one serving replica behind a
stdlib streaming HTTP endpoint.

The fleet so far is N driver threads in one process; this module is the
network boundary that makes it a distributed system. It generalizes the
PR-6 exposition-server pattern (stdlib ``ThreadingHTTPServer``, no new
dependency) from scrape-sized responses to **incremental token
streams**: one :class:`~..frontend.frontend.ServingFrontend` is exposed
over HTTP, and every placement-relevant surface the
:class:`~.router.FleetRouter` drives in process — submit / stream /
cancel / adopt, ``load_snapshot``, prefix-cache peeks, migration — has
a URL. The client half lives in :mod:`.remote`
(:class:`~.remote.RemoteReplica`); together they make the in-process
frontend the loopback case of the same protocol.

Protocol ``dstpu-fleet-v1`` — NDJSON frames over close-delimited
HTTP/1.0 streaming (no Content-Length on streams; one JSON object per
line, flushed per frame, the connection close IS the end-of-stream):

* ``POST /v1/submit``       body = submit kwargs -> token stream
* ``POST /v1/adopt``        body = ``dstpu-snapshot-v1`` + rerouted_from
                            -> replayed token stream (crash/drain
                            re-home across the wire)
* ``POST /v1/migrate_in``   body = encoded KV bundle -> continuation
                            stream from the migrated cursor
* ``POST /v1/cancel``       body = {uid} -> {ok} (the stream then ends
                            ``cancelled`` within one decode chunk)
* ``POST /v1/migrate_out``  body = {uid} -> the encoded KV bundle; the
                            original stream ends ``migrated``
* ``GET  /v1/load``         ``load_snapshot()`` (``dstpu-load-v1``)
* ``GET  /v1/prefix?key=<hex>``  prefix-cache membership peek
* ``GET  /v1/migratable``   movable uids (rebalancer input)
* ``GET  /v1/stats`` · ``/v1/trace`` · ``/v1/tenants`` · ``/healthz``
* ``GET  /v1/metrics``      Prometheus text proxy (runtime + TraceLog)
                            — the fleet aggregator's remote scrape

Stream frames (each a JSON line):

* ``{"event": "accepted", "uid", "trace_id", "start"}``
* ``{"event": "tokens", "start": N, "tokens": [...]}`` — ``start`` is
  the ABSOLUTE index of the first token in the frame, so a client that
  already holds a prefix (adopt replay, migration resume) dedups by
  position, never by guessing: zero duplicate tokens by construction.
* ``{"event": "hb"}`` — idle heartbeat; its real job is detecting a
  silently departed client (the write raises, the server cancels).
* ``{"event": "end", "status", "n_tokens", "reject_reason", "error"}``

KV bundles cross the wire as ``encode_bundle`` output: every cache
leaf base64-encoded with dtype+shape (``bfloat16`` round-trips via
``ml_dtypes``), every cursor field plain JSON. In-process migrations
skip the codec entirely — the bundle's ndarrays pass by reference.

This module never imports JAX: it must serve ``/healthz`` and
``/v1/load`` even while the device backend is wedged.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ...analysis import locks
from ...telemetry.exposition import ReusableThreadingHTTPServer
from ...utils.logging import logger
from ..engine import MigrationError
from ..frontend.admission import PRIORITY_NORMAL
from ..frontend.frontend import ServingFrontend, StreamHandle
from ..scheduler import Request

#: wire protocol version — frames and endpoint shapes above
FLEET_SCHEMA = "dstpu-fleet-v1"

NDJSON_TYPE = "application/x-ndjson"


# ----------------------------------------------------------- KV codec
def encode_bundle(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-encode a migration bundle: every ``kv`` leaf becomes
    ``{"b64", "dtype", "shape"}``; cursor fields are already plain.
    The inverse of :func:`decode_bundle`."""
    out = {k: v for k, v in bundle.items() if k != "kv"}
    kv: Dict[str, Any] = {}
    for name, arr in bundle.get("kv", {}).items():
        a = np.ascontiguousarray(arr)
        kv[name] = {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
                    "dtype": str(a.dtype), "shape": list(a.shape)}
    out["kv"] = kv
    out["kv_encoding"] = "b64-v1"
    return out


def _wire_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends: numpy only knows them through the
        # ml_dtypes registrations JAX ships with
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def decode_bundle(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`encode_bundle`. Leaves that are already
    ndarrays (the in-process no-codec path) pass through untouched."""
    out = {k: v for k, v in obj.items() if k not in ("kv", "kv_encoding")}
    kv: Dict[str, Any] = {}
    for name, spec in obj.get("kv", {}).items():
        if isinstance(spec, dict) and "b64" in spec:
            kv[name] = np.frombuffer(
                base64.b64decode(spec["b64"]),
                dtype=_wire_dtype(spec["dtype"])).reshape(spec["shape"])
        else:
            kv[name] = spec
    out["kv"] = kv
    return out


# ------------------------------------------------------------ handler
class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "dstpu-fleet/1"
    # HTTP/1.0 on purpose: close-delimited bodies make the token stream
    # framing trivial (no chunked-transfer encoder on either side)
    protocol_version = "HTTP/1.0"

    def log_message(self, *args):        # silence per-request stderr spam
        pass

    # ------------------------------------------------------- plumbing
    @property
    def rs(self) -> "ReplicaServer":
        return self.server.replica_server  # type: ignore[attr-defined]

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw.decode("utf-8")) if raw else {}

    def _send_json(self, code: int, obj: Any) -> None:
        data = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, code: int, body: str,
                   content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _open_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", NDJSON_TYPE)
        self.end_headers()               # no Content-Length: streaming

    def _frame(self, obj: Dict[str, Any]) -> None:
        self.wfile.write(json.dumps(obj).encode("utf-8") + b"\n")
        self.wfile.flush()

    # -------------------------------------------------------- streams
    def _stream_handle(self, handle: StreamHandle, cursor: int) -> None:
        """Pump one handle's tokens to the socket as NDJSON frames until
        terminal. ``cursor`` is the absolute index streaming starts at
        (0 for submit; the already-delivered prefix for adopt/migrate —
        the client holds those tokens, resending them would be the
        duplicate-token bug the ``start`` field exists to prevent).

        A client that disappears mid-stream surfaces as a send error;
        the server-side request is then cancelled so its slot frees
        within one decode chunk instead of decoding to a dead socket."""
        rs = self.rs
        rs._register(handle, self.connection)
        try:
            self._frame({"event": "accepted", "uid": int(handle.uid),
                         "trace_id": handle.trace_id,
                         "start": int(cursor)})
            last_write = time.monotonic()
            while True:
                # server-local handle: this thread is its only stream
                # consumer, so reading the internals under its own
                # condition is the blocking-iterator pattern inlined
                with handle._cond:
                    handle._cond.wait_for(
                        lambda: len(handle._tokens) > cursor
                        or handle._status is not None,
                        timeout=rs.heartbeat_s)
                    toks = [int(t) for t in handle._tokens[cursor:]]
                    status = handle._status
                if toks:
                    self._frame({"event": "tokens", "start": int(cursor),
                                 "tokens": toks})
                    cursor += len(toks)
                    last_write = time.monotonic()
                if status is not None:
                    self._frame({
                        "event": "end", "status": status,
                        "n_tokens": int(cursor),
                        "reject_reason": handle.reject_reason,
                        "error": handle.error})
                    return
                if time.monotonic() - last_write >= rs.heartbeat_s:
                    self._frame({"event": "hb"})
                    last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream: stop decoding for it
            if not handle.done:
                try:
                    handle.cancel()
                except Exception:  # noqa: BLE001 — already disconnected
                    pass
        finally:
            rs._unregister(handle, self.connection)

    # ------------------------------------------------------ endpoints
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        fe = self.rs.frontend
        url = urlparse(self.path)
        try:
            if url.path == "/healthz":
                self._send_json(200, {
                    "status": "alive", "schema": FLEET_SCHEMA,
                    "driver_alive": bool(fe.driver_alive),
                    "draining": bool(getattr(fe, "draining", False))})
            elif url.path == "/v1/load":
                self._send_json(200, fe.load_snapshot())
            elif url.path == "/v1/prefix":
                qs = parse_qs(url.query)
                key = qs.get("key", [""])[0]
                fetch = qs.get("fetch", ["0"])[0] not in ("", "0")
                holds = bool(key) and fe.holds_prefix(bytes.fromhex(key))
                if not fetch:
                    self._send_json(200, {"holds": bool(holds)})
                else:
                    # bundle-payload mode (?fetch=1): serve the demoted
                    # prefix itself — tier entries only (host-side; the
                    # device pool belongs to the engine thread), encoded
                    # with the same codec a migrated block rides
                    bundle = fe.fetch_prefix(bytes.fromhex(key)) \
                        if key else None
                    if bundle is None:
                        self._send_json(200, {"holds": bool(holds),
                                              "bundle": None})
                    else:
                        self._send_json(200, {
                            "holds": True,
                            "bundle": encode_bundle(bundle)})
            elif url.path == "/v1/migratable":
                self._send_json(200, {"uids": fe.migration_candidates()})
            elif url.path == "/v1/stats":
                self._send_json(200, fe.stats())
            elif url.path == "/v1/trace":
                self._send_json(200, fe.tracing.to_json())
            elif url.path == "/v1/tenants":
                self._send_json(200, fe.tracing.tenants_report())
            elif url.path == "/v1/metrics":
                # Prometheus proxy verb: the fleet plane's aggregator
                # scrapes remote replicas through the SAME wire the
                # router already speaks, so a replica needs no second
                # listener. Renders this process's runtime + the
                # frontend's TraceLog in text format 0.0.4.
                from ...telemetry import core as _tcore
                from ...telemetry.exposition import (CONTENT_TYPE,
                                                     render_prometheus)
                self._send_text(200, render_prometheus(
                    runtime=_tcore.get_runtime(), tracelog=fe.tracing),
                    CONTENT_TYPE)
            else:
                self._send_json(404, {"error": "not found"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — probe must not kill server
            self._safe_error(e)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        try:
            body = self._body()
            if url.path == "/v1/submit":
                self._do_submit(body)
            elif url.path == "/v1/adopt":
                self._do_adopt(body)
            elif url.path == "/v1/cancel":
                self._do_cancel(body)
            elif url.path == "/v1/migrate_out":
                self._do_migrate_out(body)
            elif url.path == "/v1/migrate_in":
                self._do_migrate_in(body)
            elif url.path == "/v1/prefix":
                # install a peer-fetched prefix bundle into the local
                # DRAM tier (no device access — it promotes through the
                # normal async path when a request for it arrives)
                ok = self.rs.frontend.install_prefix(
                    decode_bundle(body["bundle"]))
                self._send_json(200, {"ok": bool(ok)})
            else:
                self._send_json(404, {"error": "not found"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            self._safe_error(e)

    def _safe_error(self, e: Exception) -> None:
        try:
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
        except Exception:  # noqa: BLE001 — headers already sent
            pass

    def _do_submit(self, body: Dict[str, Any]) -> None:
        fe = self.rs.frontend
        handle = fe.submit(
            np.asarray(body["prompt"], np.int32),
            priority=int(body.get("priority", PRIORITY_NORMAL)),
            tenant=str(body.get("tenant", "default")),
            slo_ttft_s=body.get("slo_ttft_s"),
            deadline_s=body.get("deadline_s"),
            max_new_tokens=int(body.get("max_new_tokens", 32)),
            eos_token_id=body.get("eos_token_id"),
            trace_id=body.get("trace_id"))
        self._open_stream()
        self._stream_handle(handle, cursor=0)

    def _do_adopt(self, body: Dict[str, Any]) -> None:
        """Cross-host re-home: rebuild a server-local StreamHandle from
        the caller's ``dstpu-snapshot-v1`` and hand it to the frontend's
        existing ``adopt`` replay machinery — the stream resumes past
        the emitted prefix with zero duplicates (frames carry absolute
        ``start``)."""
        fe = self.rs.frontend
        snap = body["snapshot"]
        sampling = snap.get("sampling", {})
        req = Request(
            prompt=np.asarray(snap["prompt"], np.int32),
            max_new_tokens=int(snap["max_new_tokens"]),
            eos_token_id=sampling.get("eos_token_id"),
            deadline_s=sampling.get("deadline_s"),
            trace_id=snap.get("trace_id"),
            tenant=str(sampling.get("tenant", "default")))
        handle = StreamHandle(
            req, fe, tenant=req.tenant,
            priority=int(sampling.get("priority", PRIORITY_NORMAL)),
            slo_ttft_s=sampling.get("slo_ttft_s"),
            submit_t=fe._clock(), trace_id=snap.get("trace_id"))
        emitted = [int(t) for t in snap.get("tokens_emitted", [])]
        with handle._cond:
            handle._tokens = list(emitted)
        ok = fe.adopt(handle,
                      rerouted_from=body.get("rerouted_from"))
        if not ok:
            self._send_json(409, {
                "error": "adopt rejected",
                "reject_reason": handle.reject_reason})
            return
        self._open_stream()
        self._stream_handle(handle, cursor=len(emitted))

    def _do_cancel(self, body: Dict[str, Any]) -> None:
        handle = self.rs._live(int(body["uid"]))
        if handle is None:
            self._send_json(404, {"ok": False,
                                  "error": "unknown or finished uid"})
            return
        handle.cancel()
        self._send_json(200, {"ok": True})

    def _do_migrate_out(self, body: Dict[str, Any]) -> None:
        """Serialize-and-detach: the bundle travels back as the response
        body while the original ``/v1/submit`` stream for the uid ends
        with status ``migrated`` — the signal that the client's caller
        handle must stay open for the destination's continuation."""
        rs = self.rs
        uid = int(body["uid"])
        try:
            bundle, handle = rs.frontend.migrate_out(
                uid, timeout=rs.verb_timeout_s)
        except MigrationError as e:
            self._send_json(409, {"error": str(e)})
            return
        # terminate the server-local stream; "migrated" is non-terminal
        # on the WIRE (the client keeps its caller handle pending) but
        # terminal for this server's copy
        handle._resolve("migrated")
        self._send_json(200, encode_bundle(bundle))

    def _do_migrate_in(self, body: Dict[str, Any]) -> None:
        rs = self.rs
        bundle = decode_bundle(body["bundle"])
        try:
            handle = rs.frontend.migrate_in(
                bundle, None, migrated_from=body.get("migrated_from"),
                timeout=rs.verb_timeout_s)
        except MigrationError as e:
            self._send_json(409, {"error": str(e)})
            return
        resumed = len(bundle.get("tokens", []))
        self._open_stream()
        self._stream_handle(handle, cursor=resumed)


# ------------------------------------------------------------- server
class ReplicaServer:
    """Serve one :class:`ServingFrontend` over the fleet wire.

    Stdlib-only (the exposition-server pattern): a
    :class:`~...telemetry.exposition.ReusableThreadingHTTPServer` —
    ``SO_REUSEADDR`` + daemon request threads — with one thread per
    in-flight stream. ``port=0`` binds an ephemeral port; read the
    kernel's choice back from ``.port`` (the test/bench pattern).

    The server does not own the frontend's lifecycle: ``close()`` stops
    accepting connections and ends in-flight streams (their sockets
    close; clients see a disconnect), but the frontend keeps running
    until its owner closes it."""

    def __init__(self, frontend: ServingFrontend, *,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_s: float = 1.0,
                 verb_timeout_s: float = 30.0):
        self.frontend = frontend
        self.heartbeat_s = float(heartbeat_s)
        self.verb_timeout_s = float(verb_timeout_s)
        self._lock = locks.make_lock("fleet.transport")
        self._streams: Dict[int, StreamHandle] = {}
        self._stream_conns: Dict[int, Any] = {}  # uid -> raw socket
        self._httpd = ReusableThreadingHTTPServer((host, port),
                                                  _FleetHandler)
        self._httpd.replica_server = self  # type: ignore[attr-defined]
        self.host = host
        self.port = self._httpd.server_address[1]
        # tight poll: close() severs live streams only after shutdown()
        # returns, so the accept loop must notice the flag promptly
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            name="dstpu-fleet-server", daemon=True)
        self._thread.start()
        logger.info(f"fleet replica server listening on {self.url}")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # live-stream registry: /v1/cancel resolves uids through it, and
    # close() severs the registered sockets so a dead server looks
    # dead to its clients instead of streaming on from handler threads
    def _register(self, handle: StreamHandle, conn: Any) -> None:
        with self._lock:
            self._streams[int(handle.uid)] = handle
            self._stream_conns[int(handle.uid)] = conn

    def _unregister(self, handle: StreamHandle, conn: Any) -> None:
        with self._lock:
            self._streams.pop(int(handle.uid), None)
            self._stream_conns.pop(int(handle.uid), None)

    def _live(self, uid: int) -> Optional[StreamHandle]:
        with self._lock:
            return self._streams.get(uid)

    @property
    def n_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        # hard-sever in-flight streams: clients must see a disconnect
        # (EOF without an end frame -> their salvage path), not a
        # handler thread immortally feeding an orphaned socket
        with self._lock:
            conns = list(self._stream_conns.values())
            self._stream_conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._thread.join(timeout=5)

    def __enter__(self) -> "ReplicaServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
