"""ElasticController: SLO-driven autoscaling over a ``FleetRouter``.

DeepSpeed's ``elasticity/`` layer resized training jobs so a scheduler
could grow/shrink world size without touching convergence. This is its
serving-side successor: the fleet's replica count becomes a controlled
variable, driven by the sensors the serving tier already publishes —
per-replica SLO fast/slow burn rates (:mod:`...telemetry.slo`) and
``load_snapshot()`` drain-time estimates — instead of being fixed at
``FleetRouter`` construction.

The control loop, each tick (``step()``; ``start()`` runs it on a
daemon thread):

1. **Sense** — lazily attach one :class:`SLOEngine` per replica to its
   frontend's ``TraceLog`` (new replicas get a sensor the tick after
   they join), read every routable replica's fast-burn rate and
   estimated drain time, and finalize any retirement whose replica has
   gone idle (``FleetRouter.poll_draining``).
2. **Restore** — a crash (or an over-eager drain) that leaves fewer
   routable replicas than ``target_replicas`` is repaired immediately,
   no cooldown: ``add_replica()`` builds a fresh engine from the
   router's ``replica_factory`` (checkpoint-backed — committed params,
   nothing to transfer) and warm-starts its EWMA from a peer.
3. **Scale up** — fast burn at/above ``scale_up_fast_burn`` (the
   page-worthy threshold), or every replica's drain-time estimate above
   ``scale_up_drain_s``, grows the fleet by one (bounded by
   ``max_replicas``, rate-limited by ``cooldown_s``).
4. **Scale down** — fast burn at/below ``scale_down_fast_burn`` with
   more routable replicas than the target retires the least-loaded one
   *gracefully*: placement stops instantly, the admission tail is
   adopted by survivors, in-engine chunks retire naturally, and the
   retirement completes via ``poll_draining`` on a later tick.

``fleet/target_size`` is exported as a gauge every tick; scale actions
land on the ``fleet/scale_up|scale_down|drained`` counters the router
owns. Host-side only — never imports JAX.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

from ...analysis import locks
from ...telemetry import core as telemetry
from ...telemetry.slo import SLOEngine, SLOSpec
from ...utils.logging import logger


@dataclass
class ElasticConfig:
    """Autoscaler policy knobs.

    ``target_replicas`` is the steady-state fleet size (None = the
    router's routable count when the controller first steps). Burn
    thresholds are in SLO burn-rate units: 1.0 = exactly on error
    budget; the stock page-worthy fast burn is ~2. ``scale_up_drain_s``
    optionally adds a load-based growth trigger: grow when even the
    least-loaded replica would take this long to drain its backlog.
    ``cooldown_s`` rate-limits burn/load-driven actions; restoring a
    below-target fleet (crash repair) never waits."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_replicas: Optional[int] = None
    scale_up_fast_burn: float = 2.0
    scale_down_fast_burn: float = 0.5
    scale_up_drain_s: Optional[float] = None
    cooldown_s: float = 5.0
    poll_every_s: float = 0.25
    # live KV-block migration between ticks' scale actions: when on, a
    # tick that takes no scale action instead asks the router to
    # rebalance one running request from the busiest to the idlest
    # replica once their running-count spread reaches
    # ``rebalance_spread`` (off by default: migration moves device
    # state — deployments opt in)
    rebalance: bool = False
    rebalance_spread: int = 2


class ElasticController:
    """Drive ``FleetRouter.add_replica``/``retire_replica`` from SLO
    burn + drain-time sensors.

    ``slos``/``windows_s`` configure the per-replica :class:`SLOEngine`
    sensors (defaults: the stock serving SLOs over 60 s/300 s windows;
    benches pass tighter windows so burn moves within a run). ``step()``
    is the whole control loop for one tick — tests and benches call it
    directly; ``start()``/``stop()`` wrap it in a daemon thread for
    real deployments."""

    def __init__(self, router: Any,
                 config: Optional[ElasticConfig] = None, *,
                 slos: Optional[Iterable[SLOSpec]] = None,
                 windows_s: Iterable[float] = (60.0, 300.0),
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.config = config or ElasticConfig()
        self._slos = list(slos) if slos is not None else None
        self._windows_s = tuple(windows_s)
        self._clock = clock
        self._lock = locks.make_lock("fleet.elastic")
        self._sensors: Dict[int, SLOEngine] = {}
        self.target: Optional[int] = self.config.target_replicas
        self._last_action_t: Optional[float] = None
        self.n_steps = 0
        self.actions: List[Dict[str, Any]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ----------------------------------------------------------- sensors
    def _ensure_sensors(self) -> None:
        """One SLOEngine per replica, attached to its frontend's
        TraceLog; replicas added after construction get theirs on the
        next tick."""
        for rep in list(self.router.replicas):
            if rep.rid not in self._sensors:
                tracing = rep.frontend.tracing
                if not hasattr(tracing, "add_listener"):
                    # remote replica: its TraceLog lives server-side —
                    # its own controller senses it there
                    continue
                eng = SLOEngine(self._slos, windows_s=self._windows_s,
                                clock=self._clock)
                eng.attach(tracing)
                self._sensors[rep.rid] = eng

    def burn_rates(self) -> Dict[int, float]:
        """Fast-burn rate per ALIVE replica (draining included — their
        in-flight tail still burns budget)."""
        out: Dict[int, float] = {}
        for rep in list(self.router.replicas):
            if rep.alive and rep.rid in self._sensors:
                out[rep.rid] = self._sensors[rep.rid].fast_burn_rate()
        return out

    def drain_times(self) -> Dict[int, float]:
        """Estimated seconds for each ROUTABLE replica to drain its
        outstanding work (the router's load score)."""
        return {rep.rid: float(self.router._load_score(rep))
                for rep in list(self.router.replicas) if rep.routable}

    def sensor(self, rid: int) -> Optional[SLOEngine]:
        with self._lock:
            return self._sensors.get(rid)

    # ------------------------------------------------------ control loop
    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One control tick: sense, finalize drains, and take at most
        one scale action. Returns the decision record."""
        cfg = self.config
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._ensure_sensors()
            if self.target is None:
                self.target = max(cfg.min_replicas,
                                  self.router.n_routable)
            retired = self.router.poll_draining()
            burns = self.burn_rates()
            drains = self.drain_times()
            routable = self.router.n_routable
            fast_burn = max(burns.values(), default=0.0)
            min_drain = min(drains.values(), default=0.0)
            telemetry.gauge("fleet/target_size", float(self.target))
            in_cooldown = (self._last_action_t is not None
                           and now - self._last_action_t < cfg.cooldown_s)
            action, reason = "none", None
            if routable < self.target:
                # crash repair / drain overshoot: restore immediately
                action, reason = self._try_add("below_target")
            elif (not in_cooldown and routable < cfg.max_replicas
                  and (fast_burn >= cfg.scale_up_fast_burn
                       or (cfg.scale_up_drain_s is not None and drains
                           and min_drain > cfg.scale_up_drain_s))):
                action, reason = self._try_add(
                    "fast_burn" if fast_burn >= cfg.scale_up_fast_burn
                    else "drain_time")
            elif (not in_cooldown and routable > self.target
                  and routable > cfg.min_replicas
                  and fast_burn <= cfg.scale_down_fast_burn):
                rep = self.router.retire_replica(
                    min_routable=max(cfg.min_replicas, self.target))
                if rep is not None:
                    action, reason = "scale_down", "above_target_calm"
            if action == "none" and cfg.rebalance:
                moves = self.router.rebalance(
                    spread_threshold=cfg.rebalance_spread)
                if moves:
                    action, reason = "rebalance", "occupancy_spread"
            if action != "none":
                self._last_action_t = now
            self.n_steps += 1
            record = {"t": now, "action": action, "reason": reason,
                      "routable": self.router.n_routable,
                      "target": self.target, "fast_burn": fast_burn,
                      "burns": burns, "drain_s": drains,
                      "retired": retired}
            if action != "none":
                self.actions.append(record)
                logger.info(f"elastic controller: {action} ({reason}) "
                            f"routable={record['routable']} "
                            f"target={self.target} "
                            f"fast_burn={fast_burn:.2f}")
            return record

    def _try_add(self, reason: str):
        """Grow by one replica via the router's factory; a fleet built
        without one simply can't grow (the decision records why)."""
        if self.router.replica_factory is None:
            return "none", "no_replica_factory"
        if len([r for r in self.router.replicas if r.routable]) \
                >= self.config.max_replicas:
            return "none", "at_max_replicas"
        self.router.add_replica()
        return "scale_up", reason

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ElasticController":
        """Run ``step()`` every ``poll_every_s`` on a daemon thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="elastic-controller", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error(f"elastic controller step failed: {e}")
            self._stop.wait(self.config.poll_every_s)

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "ElasticController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ queries
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "target": self.target,
                "n_steps": self.n_steps,
                "n_actions": len(self.actions),
                "actions": [dict(a) for a in self.actions],
                "sensors": sorted(self._sensors),
            }


def elastic_config_from_elasticity(ds_config: dict, *, n_pods: int = 1,
                                   **overrides) -> ElasticConfig:
    """Parse a DeepSpeed ``elasticity`` config block into a per-pod
    serving :class:`ElasticConfig` — the heritage surface wired to the
    fleet instead of lying dormant.

    The training-side schedule constrains which WORLD SIZES (device
    counts) the resource scheduler may run the job at:
    ``compute_elastic_config`` picks the batch size admitting the most
    valid worlds, and min/max of that valid set are the schedule's
    hard replica bounds. Serving maps those fleet-wide bounds onto
    ``n_pods`` equal pods (ceil-divided, so the pods together can
    always reach the fleet-wide max), and the smallest valid world is
    the steady-state target:

    * ``min_replicas``  = max(1, min(valid_worlds) // n_pods)
    * ``max_replicas``  = ceil(max(valid_worlds) / n_pods)
    * ``target_replicas`` defaults to ``min_replicas`` (grow on burn)

    ``min_time`` and ``ignore_non_elastic_batch_info`` are parsed by
    :class:`~...elasticity.elasticity.ElasticityConfig` for schema
    compatibility but have no serving-side behavior (there is no train
    loop to time and no non-elastic batch block to ignore) — they are
    accepted and logged, never silently load-bearing. Keyword
    ``overrides`` pass through to :class:`ElasticConfig` (burn
    thresholds, cooldown, ...) after the schedule-derived fields."""
    # function-local import: ``elasticity/__init__`` re-exports THIS
    # module's classes, so a top-level import would be circular
    from ...elasticity.elasticity import (ElasticityConfig,
                                          compute_elastic_config)
    if n_pods < 1:
        raise ValueError(f"n_pods must be >= 1, got {n_pods}")
    ec = ElasticityConfig(ds_config.get("elasticity", {}))
    if ec.min_time:
        logger.info("elasticity.min_time has no serving-side effect "
                    "(no train loop to time); ignoring")
    if ec.ignore_non_elastic_batch_info:
        logger.info("elasticity.ignore_non_elastic_batch_info has no "
                    "serving-side effect; ignoring")
    _, valid_worlds = compute_elastic_config(ds_config)[:2]
    if not valid_worlds:
        raise ValueError("elasticity schedule admits no valid world "
                         "sizes — nothing to scale between")
    lo, hi = min(valid_worlds), max(valid_worlds)
    fields = {
        "min_replicas": max(1, lo // n_pods),
        "max_replicas": max(1, -(-hi // n_pods)),
        "target_replicas": max(1, lo // n_pods),
    }
    fields.update(overrides)
    cfg = ElasticConfig(**fields)
    logger.info(f"elasticity schedule -> per-pod ElasticConfig: worlds "
                f"{lo}..{hi} over {n_pods} pod(s) -> "
                f"min={cfg.min_replicas} max={cfg.max_replicas} "
                f"target={cfg.target_replicas}")
    return cfg
