"""Fleet serving: data-parallel replica routing over serving engines.

One ``ServingEngine`` is one replica; a deployment runs N of them
(optionally tensor-parallel via the engine's ``tp=`` knob, optionally
prefill/decode-disaggregated via ``disaggregate_prefill=True``) behind
one :class:`FleetRouter` — least-loaded placement, prefix-affinity
routing, dead-replica drain with in-flight replay, and SLO-driven
elastic sizing via :class:`ElasticController`. Replicas need not share
the process: :class:`ReplicaServer` exposes one frontend over the
``dstpu-fleet-v1`` streaming HTTP transport and :class:`RemoteReplica`
drives it from the router's side (``FleetRouter.add_remote``), with
live KV-block migration (``FleetRouter.migrate`` / ``rebalance``)
re-homing running requests across the wire mid-decode. See
docs/serving.md.
"""

from .elastic import ElasticConfig, ElasticController  # noqa: F401
from .router import FleetReplica, FleetRouter  # noqa: F401
from .transport import (FLEET_SCHEMA, ReplicaServer,  # noqa: F401
                        decode_bundle, encode_bundle)
from .remote import RemoteReplica  # noqa: F401

__all__ = ["FleetRouter", "FleetReplica",
           "ElasticController", "ElasticConfig",
           "ReplicaServer", "RemoteReplica", "FLEET_SCHEMA",
           "encode_bundle", "decode_bundle"]
