"""Fleet serving: data-parallel replica routing over serving engines.

One ``ServingEngine`` is one replica; a deployment runs N of them
(optionally tensor-parallel via the engine's ``tp=`` knob, optionally
prefill/decode-disaggregated via ``disaggregate_prefill=True``) behind
one :class:`FleetRouter` — least-loaded placement, prefix-affinity
routing, dead-replica drain with in-flight replay, and SLO-driven
elastic sizing via :class:`ElasticController`. Replicas need not share
the process: :class:`ReplicaServer` exposes one frontend over the
``dstpu-fleet-v1`` streaming HTTP transport and :class:`RemoteReplica`
drives it from the router's side (``FleetRouter.add_remote``), with
live KV-block migration (``FleetRouter.migrate`` / ``rebalance``)
re-homing running requests across the wire mid-decode.

Beyond one flat router, :mod:`.hierarchy` scales placement two-level:
:class:`LeafRouter` pods behind one :class:`RootRouter` placing by
consistent-hash prefix→pod over cached pod aggregates, with cross-pod
migration/failover and per-pod elastic policy. :mod:`.sim` is the
deterministic discrete-event simulator that validates the whole
control plane at 1000 replicas (chaos injection included) without an
engine in sight. See docs/serving.md.
"""

from .elastic import (ElasticConfig, ElasticController,  # noqa: F401
                      elastic_config_from_elasticity)
from .hierarchy import (ConsistentHashRing, LeafRouter,  # noqa: F401
                        REJECT_POD_OVERLOADED, RootConfig, RootRouter)
from .router import FleetReplica, FleetRouter  # noqa: F401
from .transport import (FLEET_SCHEMA, ReplicaServer,  # noqa: F401
                        decode_bundle, encode_bundle)
from .remote import RemoteReplica  # noqa: F401
from .sim import (ChaosInjector, FleetWatchdog, SimClock,  # noqa: F401
                  SimReplica, SimReplicaConfig, SimWorld,
                  build_sim_fleet, diurnal_trace, export_sim_trace,
                  hot_prefix_storm, multi_turn_trace, run_trace,
                  sim_expected, sim_trace_events, tenant_skew_trace,
                  verify_streams)

__all__ = ["FleetRouter", "FleetReplica",
           "ElasticController", "ElasticConfig",
           "elastic_config_from_elasticity",
           "ReplicaServer", "RemoteReplica", "FLEET_SCHEMA",
           "encode_bundle", "decode_bundle",
           "ConsistentHashRing", "LeafRouter", "RootRouter",
           "RootConfig", "REJECT_POD_OVERLOADED",
           "SimClock", "SimWorld", "SimReplica", "SimReplicaConfig",
           "FleetWatchdog", "ChaosInjector", "build_sim_fleet",
           "run_trace", "verify_streams", "sim_expected",
           "sim_trace_events", "export_sim_trace",
           "diurnal_trace", "tenant_skew_trace", "hot_prefix_storm",
           "multi_turn_trace"]
