"""Fleet serving: data-parallel replica routing over serving engines.

One ``ServingEngine`` is one replica; a deployment runs N of them
(optionally tensor-parallel via the engine's ``tp=`` knob, optionally
prefill/decode-disaggregated via ``disaggregate_prefill=True``) behind
one :class:`FleetRouter` — least-loaded placement, prefix-affinity
routing, dead-replica drain with in-flight replay, and SLO-driven
elastic sizing via :class:`ElasticController`. See docs/serving.md.
"""

from .elastic import ElasticConfig, ElasticController  # noqa: F401
from .router import FleetReplica, FleetRouter  # noqa: F401

__all__ = ["FleetRouter", "FleetReplica",
           "ElasticController", "ElasticConfig"]
