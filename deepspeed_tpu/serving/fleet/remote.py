"""RemoteReplica: a fleet replica on the far side of the wire.

The client half of the ``dstpu-fleet-v1`` transport
(:mod:`.transport`): one :class:`RemoteReplica` speaks to one
:class:`~.transport.ReplicaServer` and satisfies the exact surface
:class:`~.router.FleetRouter` drives on an in-process
:class:`~..frontend.frontend.ServingFrontend` — ``submit`` returning a
live :class:`~..frontend.frontend.StreamHandle`, ``cancel``, ``adopt``,
``load_snapshot``, ``holds_prefix``, ``stats``, the tracing read
surface, ``driver_alive``, and the migration verbs. Placement logic
(health → prefix affinity → least-loaded) therefore does not know or
care which replicas are loopback and which are remote.

Each submit spawns one reader thread that pumps the server's NDJSON
frames into the caller's handle. Dedup is positional: every ``tokens``
frame carries the ABSOLUTE index of its first token, the reader skips
whatever prefix the handle already holds, and a frame that would leave
a gap resolves the handle to a structured ``error`` — duplicated or
lost tokens cannot happen silently.

Failure semantics mirror the in-process fleet: a single broken stream
resolves just that handle (``error``) — unless the replica's
``/healthz`` has also gone dark, in which case the replica is marked
dead and EVERY live handle is salvaged through the same ``on_crash``
hook a crashing in-process driver fires, so the router's existing
re-home/replay path (``adopt`` + emitted-token dedup) covers dead
remotes with zero duplicate tokens.

Host-side only — never imports JAX.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ...analysis import locks
from ...utils.logging import logger
from ..engine import MigrationError
from ..frontend.admission import PRIORITY_NORMAL, REJECT_FRONTEND_CLOSED
from ..frontend.frontend import (LOAD_SCHEMA, ServingFrontend,
                                 StreamHandle)
from ..scheduler import Request
from .transport import decode_bundle, encode_bundle


class _RemoteTracing:
    """Read-only tracing shim: the router's journey/tenant exports pull
    ``to_json()``/``tenants_report()`` from every replica; for a remote
    one they are HTTP reads of the server's own TraceLog."""

    def __init__(self, remote: "RemoteReplica"):
        self._remote = remote

    def to_json(self) -> Dict[str, Any]:
        return self._remote._get_json(
            "/v1/trace",
            default={"histograms": {}, "counters": {},
                     "requests": [], "live": []})

    def tenants_report(self) -> Dict[str, Any]:
        return self._remote._get_json(
            "/v1/tenants",
            default={"schema": "dstpu-tenants-v1", "n_tenants": 0,
                     "tenants": {}})


class RemoteReplica:
    """Client handle for one remote serving replica.

    Constructed from the server's address; plugs into
    ``FleetRouter.add_remote()``, which installs the router's crash
    hook on ``on_crash`` and wraps it in a ``FleetReplica`` with
    ``engine=None`` (every engine-shaped probe goes over the wire
    instead)."""

    #: bound on cached ``holds_prefix`` answers (hot prompts are few;
    #: this only exists so a key-diverse workload can't grow the map)
    PREFIX_CACHE_CAP = 1024

    def __init__(self, host: str, port: int, *,
                 label: Optional[str] = None,
                 timeout_s: float = 30.0,
                 health_ttl_s: float = 0.5,
                 snapshot_ttl_s: float = 0.25,
                 clock=time.monotonic):
        self.host = host
        self.port = int(port)
        self.label = label or f"{host}:{port}"
        self.timeout_s = float(timeout_s)
        self.health_ttl_s = float(health_ttl_s)
        # placement-probe cache TTL: load_snapshot()/holds_prefix() are
        # synchronous HTTP GETs, and the router calls BOTH per replica
        # per submit — uncached, placement latency scales with remote
        # count. Staleness is bounded by the TTL AND by invalidation on
        # every local state-changing event (submit, accepted/end
        # frames, adopt, migrate, install_prefix). 0 disables caching.
        self.snapshot_ttl_s = float(snapshot_ttl_s)
        self._clock = clock
        # router-facing lifecycle attrs (FleetReplica/retire contract)
        self.draining = False
        self.postmortem_path: Optional[str] = None
        self.on_crash = None
        self.tracing = _RemoteTracing(self)
        self.n_submitted = 0
        self._lock = locks.make_lock("fleet.remote")
        self._handles: Dict[int, StreamHandle] = {}  # remote uid -> handle
        self._readers: List[threading.Thread] = []
        self._closed = False
        self._dead = False
        # handles the crash hook took ownership of: their re-homed
        # streams are still pending, so the reader threads that saw the
        # disconnect must NOT error-resolve them (id(handle) members)
        self._salvaged: set = set()
        self._health_ok: Optional[bool] = None
        self._health_t = 0.0
        self._load_cache: Optional[Dict[str, Any]] = None
        self._load_t = float("-inf")
        # key -> (holds, probe time)
        self._prefix_cache: Dict[bytes, tuple] = {}

    def _snapshots_invalidate(self) -> None:
        """Drop the cached placement probes — called on every event
        that changes what they would report (a submit landed, a stream
        ended, an adoption/migration moved work, a prefix installed),
        so the cache can only be stale about REMOTE-initiated changes,
        and those only within ``snapshot_ttl_s``."""
        with self._lock:
            self._load_cache = None
            self._load_t = float("-inf")
            self._prefix_cache.clear()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------ HTTP plumbing
    def _conn(self,
              timeout: Optional[float] = None) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout is None else timeout)

    _RAISE = object()

    def _get_json(self, path: str, default: Any = _RAISE) -> Any:
        try:
            conn = self._conn()
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    raise ConnectionError(
                        f"GET {path} -> {resp.status}")
                return json.loads(data.decode("utf-8"))
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — degrade per `default`
            if default is self._RAISE:
                raise
            logger.debug(f"remote replica {self.label}: GET {path} "
                         f"failed ({e}); using default")
            return default

    def _get_text(self, path: str) -> str:
        """Plain-text GET (the Prometheus proxy verb is text format
        0.0.4, not JSON). Always raises on failure — the fleet
        aggregator treats a failed scrape as a dark replica."""
        conn = self._conn()
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise ConnectionError(f"GET {path} -> {resp.status}")
            return data.decode("utf-8")
        finally:
            conn.close()

    def fetch_metrics(self) -> str:
        """The fleet aggregator's remote scrape: the server-side
        process's full Prometheus exposition (runtime + TraceLog) via
        ``GET /v1/metrics``."""
        return self._get_text("/v1/metrics")

    def _post_json(self, path: str, body: Dict[str, Any]) -> Any:
        conn = self._conn()
        try:
            conn.request("POST", path, json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            payload = json.loads(data.decode("utf-8")) if data else {}
            if resp.status != 200:
                raise MigrationError(
                    payload.get("error",
                                f"POST {path} -> {resp.status}"))
            return payload
        finally:
            conn.close()

    # ---------------------------------------------------------- streaming
    def _open_stream(self, path: str, body: Dict[str, Any]):
        """POST and read frames until the first ``accepted``/``end``;
        returns ``(conn, resp, first_frame)``. Caller owns the
        connection."""
        conn = self._conn()
        try:
            conn.request("POST", path, json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                data = resp.read()
                try:
                    err = json.loads(data.decode("utf-8"))
                except Exception:  # noqa: BLE001
                    err = {"error": f"POST {path} -> {resp.status}"}
                conn.close()
                return None, None, err
            line = resp.readline()
            if not line:
                conn.close()
                return None, None, {"error": "stream closed before "
                                             "first frame"}
            return conn, resp, json.loads(line.decode("utf-8"))
        except Exception:
            conn.close()
            raise

    def _attach(self, handle: StreamHandle, remote_uid: int) -> None:
        with self._lock:
            handle._remote_uid = remote_uid
            self._handles[remote_uid] = handle
        self._snapshots_invalidate()

    def _spawn_reader(self, conn, resp, handle: StreamHandle) -> None:
        t = threading.Thread(
            target=self._read_stream, args=(conn, resp, handle),
            name=f"dstpu-remote-{self.label}", daemon=True)
        with self._lock:
            self._readers = [r for r in self._readers if r.is_alive()]
            self._readers.append(t)
        t.start()

    def _read_stream(self, conn, resp, handle: StreamHandle) -> None:
        try:
            ended = self._pump_frames(resp, handle)
            if not ended and not handle.done:
                # close-delimited protocol: EOF without an `end` frame
                # is a mid-stream disconnect, never a clean finish
                raise ConnectionError("stream closed without end frame")
        except Exception as e:  # noqa: BLE001 — resolve, never hang
            self._stream_failed(handle, e)
        finally:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
            with self._lock:
                uid = getattr(handle, "_remote_uid", None)
                if uid is not None and handle.done:
                    self._handles.pop(uid, None)
            # a stream ended (or broke): the remote's load changed
            self._snapshots_invalidate()

    def _pump_frames(self, resp, handle: StreamHandle) -> bool:
        """Apply frames to the handle; True once an ``end`` frame
        terminates the stream (including the ``migrated`` pseudo-end,
        which leaves the handle pending for the destination replica's
        continuation)."""
        for raw in iter(resp.readline, b""):
            raw = raw.strip()
            if not raw:
                continue
            frame = json.loads(raw.decode("utf-8"))
            ev = frame.get("event")
            if ev == "tokens":
                start = int(frame["start"])
                toks = [int(t) for t in frame["tokens"]]
                have = len(handle.tokens)
                skip = have - start
                if skip < 0:
                    handle._resolve(
                        "error",
                        error=f"transport token gap: frame starts at "
                              f"{start}, handle holds {have}")
                    return True
                if skip < len(toks):
                    handle._push(toks[skip:])
            elif ev == "accepted":
                self._attach(handle, int(frame["uid"]))
                if getattr(handle, "_cancel_requested", False):
                    self._post_cancel(int(frame["uid"]))
            elif ev == "end":
                status = frame.get("status")
                if status == "migrated":
                    # detached, not finished: the router re-homes this
                    # handle via migrate_in on another replica
                    return True
                if status == "rejected":
                    handle._resolve(
                        "rejected",
                        reject_reason=frame.get("reject_reason"))
                elif status == "error":
                    handle._resolve("error", error=frame.get("error"))
                else:
                    handle._resolve(status)
                return True
            # "hb" frames: liveness only, nothing to apply
        return False

    def _stream_failed(self, handle: StreamHandle, exc: Exception) -> None:
        """One broken stream: structured error for that handle — unless
        the whole replica is gone, in which case the crash-salvage path
        re-homes every live handle instead."""
        if self._probe_health(force=True):
            if not handle.done:
                handle._resolve(
                    "error",
                    error=f"transport stream failed: "
                          f"{type(exc).__name__}: {exc}")
            return
        self._mark_dead(exc)
        with self._lock:
            salvaged = id(handle) in self._salvaged
        if not salvaged and not handle.done:
            # no hook took it (or the hook declined): never hang
            handle._resolve(
                "error",
                error=f"remote replica {self.label} died: "
                      f"{type(exc).__name__}: {exc}")

    # ------------------------------------------------------- health/crash
    def _probe_health(self, force: bool = False) -> bool:
        now = self._clock()
        with self._lock:
            if self._closed or self._dead:
                return False
            if not force and self._health_ok is not None \
                    and now - self._health_t < self.health_ttl_s:
                return self._health_ok
        ok = False
        try:
            payload = self._get_json("/healthz")
            ok = bool(payload.get("driver_alive", False))
        except Exception:  # noqa: BLE001 — unreachable == not alive
            ok = False
        with self._lock:
            self._health_ok = ok
            self._health_t = now
        return ok

    def _mark_dead(self, exc: Exception) -> None:
        """Salvage every live handle through ``on_crash`` — the same
        hook a crashing in-process driver fires, so the router's
        re-home/replay path covers dead remotes unchanged."""
        with self._lock:
            if self._dead or self._closed:
                return
            self._dead = True
            handles = [h for h in self._handles.values() if not h.done]
            self._handles.clear()
            if self.on_crash is not None:
                # claimed for the hook atomically with _dead: any other
                # reader thread that sees the replica dead also sees
                # these handles as spoken for
                self._salvaged.update(id(h) for h in handles)
        logger.error(f"remote replica {self.label} is unreachable "
                     f"({type(exc).__name__}: {exc}); salvaging "
                     f"{len(handles)} live streams")
        if self.on_crash is not None and handles:
            try:
                self.on_crash(self, handles, exc)
                return
            except Exception as hook_exc:  # noqa: BLE001 — fall through
                logger.error(f"remote crash hook failed: {hook_exc}")
                with self._lock:
                    self._salvaged.difference_update(
                        id(h) for h in handles)
        msg = f"{type(exc).__name__}: {exc}"
        for h in handles:
            h._resolve("error",
                       error=f"remote replica died ({msg}) and no "
                             f"survivor adopted the request")

    # ------------------------------------------------- frontend surface
    @property
    def driver_alive(self) -> bool:
        """Cached ``/healthz`` probe — the same readiness signal the
        router checks on in-process frontends, at wire latency."""
        return self._probe_health()

    @property
    def crashed(self) -> bool:
        with self._lock:
            return self._dead

    def submit(self, prompt: Union[Sequence[int], np.ndarray], *,
               priority: int = PRIORITY_NORMAL,
               tenant: str = "default",
               slo_ttft_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               trace_id: Optional[str] = None) -> StreamHandle:
        """Same contract as ``ServingFrontend.submit``: returns a live
        StreamHandle immediately; rejections resolve it, never raise.
        The handle's ``uid`` is local; the server-side uid rides on
        ``_remote_uid`` once the ``accepted`` frame lands."""
        prompt = np.asarray(prompt, np.int32)
        req = Request(prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id, deadline_s=None,
                      trace_id=trace_id, tenant=tenant)
        handle = StreamHandle(req, self, tenant=tenant,
                              priority=priority, slo_ttft_s=slo_ttft_s,
                              submit_t=self._clock(), trace_id=trace_id)
        handle._remote_uid = None
        handle._cancel_requested = False
        self.n_submitted += 1
        with self._lock:
            dead = self._closed or self._dead
        if dead:
            handle._resolve("rejected",
                            reject_reason=REJECT_FRONTEND_CLOSED)
            return handle
        body = {"prompt": [int(t) for t in prompt],
                "priority": int(priority), "tenant": tenant,
                "slo_ttft_s": slo_ttft_s, "deadline_s": deadline_s,
                "max_new_tokens": int(max_new_tokens),
                "eos_token_id": eos_token_id, "trace_id": trace_id}
        self._snapshots_invalidate()
        t = threading.Thread(
            target=self._submit_stream, args=(body, handle),
            name=f"dstpu-remote-{self.label}", daemon=True)
        with self._lock:
            self._readers = [r for r in self._readers if r.is_alive()]
            self._readers.append(t)
        t.start()
        return handle

    def _submit_stream(self, body: Dict[str, Any],
                       handle: StreamHandle) -> None:
        try:
            conn, resp, first = self._open_stream("/v1/submit", body)
            if conn is None:
                handle._resolve("error", error=first.get("error"))
                return
            ended = self._apply_first(first, handle)
            if not ended:
                self._read_stream(conn, resp, handle)
            else:
                conn.close()
                self._snapshots_invalidate()
        except Exception as e:  # noqa: BLE001
            self._stream_failed(handle, e)

    def _apply_first(self, frame: Dict[str, Any],
                     handle: StreamHandle) -> bool:
        """First frame is ``accepted`` on the happy path; anything
        terminal short-circuits. Returns True when the stream already
        ended."""
        if frame.get("event") == "accepted":
            self._attach(handle, int(frame["uid"]))
            if getattr(handle, "_cancel_requested", False):
                self._post_cancel(int(frame["uid"]))
            return False
        if frame.get("event") == "end":
            status = frame.get("status", "error")
            if status == "rejected":
                handle._resolve("rejected",
                                reject_reason=frame.get("reject_reason"))
            else:
                handle._resolve(status if status != "migrated"
                                else "error",
                                error=frame.get("error"))
            return True
        return False

    def cancel(self, handle: StreamHandle) -> None:
        """StreamHandle.cancel() lands here (the handle's ``_frontend``
        is this replica): forward to ``POST /v1/cancel`` once the
        remote uid is known; the server frees the slot within one chunk
        and the stream ends ``cancelled``."""
        if handle.done:
            return
        handle._cancel_requested = True
        uid = getattr(handle, "_remote_uid", None)
        if uid is not None:
            self._post_cancel(uid)

    def _post_cancel(self, uid: int) -> None:
        self._snapshots_invalidate()
        try:
            self._post_json("/v1/cancel", {"uid": int(uid)})
        except Exception as e:  # noqa: BLE001 — stream/health paths win
            logger.debug(f"remote cancel uid={uid} failed: {e}")

    def adopt(self, handle: StreamHandle,
              rerouted_from: Optional[str] = None) -> bool:
        """Re-home a (possibly mid-stream) handle from a dead or
        draining peer onto the remote: ship the ``dstpu-snapshot-v1``,
        let the server replay prompt + emitted prefix, and keep
        streaming fresh tokens into the SAME handle. Positional dedup
        guarantees zero duplicates. Returns False when the remote
        declines (the router falls back)."""
        if handle.done:
            return False
        with self._lock:
            if self._closed or self._dead or self.draining:
                return False
        snap = ServingFrontend._handle_snapshot(handle)
        body = {"snapshot": snap, "rerouted_from": rerouted_from}
        try:
            conn, resp, first = self._open_stream("/v1/adopt", body)
        except Exception as e:  # noqa: BLE001 — decline, router falls back
            logger.debug(f"remote adopt failed: {e}")
            return False
        if conn is None or first.get("event") != "accepted":
            if conn is not None:
                conn.close()
            return False
        handle._frontend = self
        handle._cancel_requested = False
        self._attach(handle, int(first["uid"]))
        self.n_submitted += 1
        self._spawn_reader(conn, resp, handle)
        return True

    # ------------------------------------------------------- migration
    def migration_candidates(self) -> List[int]:
        return [int(u) for u in
                self._get_json("/v1/migratable",
                               default={"uids": []}).get("uids", [])]

    def migrate_out(self, uid: int,
                    timeout: Optional[float] = None):
        """Detach one running request from the remote: returns
        ``(bundle, handle)`` where ``handle`` is the local caller
        handle this client holds for the remote uid (its server stream
        ends ``migrated`` and the reader leaves it pending for the
        destination's continuation)."""
        with self._lock:
            handle = self._handles.get(int(uid))
        if handle is None:
            raise MigrationError(
                f"uid {uid} is not streamed through this client")
        payload = self._post_json("/v1/migrate_out", {"uid": int(uid)})
        with self._lock:
            self._handles.pop(int(uid), None)
        self._snapshots_invalidate()
        return decode_bundle(payload), handle

    def migrate_in(self, bundle: Dict[str, Any],
                   handle: Optional[StreamHandle] = None, *,
                   migrated_from: Optional[str] = None,
                   timeout: Optional[float] = None) -> StreamHandle:
        """Re-home an exported request onto the remote and resume
        streaming into ``handle`` (minted locally when None). The
        server's continuation frames start at the resumed cursor;
        positional dedup keeps the caller's stream gapless."""
        body = {"bundle": encode_bundle(bundle),
                "migrated_from": migrated_from}
        conn, resp, first = self._open_stream("/v1/migrate_in", body)
        if conn is None:
            raise MigrationError(first.get("error", "migrate_in failed"))
        if first.get("event") != "accepted":
            conn.close()
            raise MigrationError(
                f"unexpected first frame: {first!r}")
        if handle is None:
            req = Request(
                prompt=np.asarray(bundle["prompt"], np.int32),
                max_new_tokens=int(bundle["max_new_tokens"]),
                eos_token_id=bundle.get("eos_token_id"),
                deadline_s=bundle.get("deadline_s"),
                trace_id=bundle.get("trace_id"),
                tenant=str(bundle.get("tenant", "default")))
            handle = StreamHandle(
                req, self, tenant=req.tenant, priority=PRIORITY_NORMAL,
                slo_ttft_s=None, submit_t=self._clock(),
                trace_id=req.trace_id)
            with handle._cond:
                # resumed prefix was delivered at the source; keep the
                # buffer's absolute indexing continuous, park the
                # read cursor past it
                handle._tokens = [int(t) for t in bundle["tokens"]]
                handle._cursor = len(handle._tokens)
        handle._frontend = self
        handle._cancel_requested = False
        self._attach(handle, int(first["uid"]))
        self.n_submitted += 1
        self._spawn_reader(conn, resp, handle)
        return handle

    # --------------------------------------------------------- queries
    def holds_prefix(self, key: bytes) -> bool:
        now = self._clock()
        with self._lock:
            hit = self._prefix_cache.get(key)
            if hit is not None and now - hit[1] < self.snapshot_ttl_s:
                return hit[0]
        holds = bool(self._get_json(
            f"/v1/prefix?key={key.hex()}",
            default={"holds": False}).get("holds", False))
        with self._lock:
            while len(self._prefix_cache) >= self.PREFIX_CACHE_CAP:
                self._prefix_cache.pop(next(iter(self._prefix_cache)))
            self._prefix_cache[key] = (holds, now)
        return holds

    def fetch_prefix(self, key: bytes) -> Optional[Dict[str, Any]]:
        """``GET /v1/prefix?fetch=1`` — pull the remote's demoted prefix
        payload (decoded ``dstpu-prefix-v1`` bundle), or None when the
        remote holds nothing fetchable."""
        payload = self._get_json(
            f"/v1/prefix?key={key.hex()}&fetch=1",
            default={"bundle": None}).get("bundle")
        return None if payload is None else decode_bundle(payload)

    def install_prefix(self, bundle: Dict[str, Any]) -> bool:
        """``POST /v1/prefix`` — install a fetched prefix bundle into
        the remote's DRAM tier."""
        ok = bool(self._post_json(
            "/v1/prefix",
            {"bundle": encode_bundle(bundle)}).get("ok", False))
        if ok:
            self._snapshots_invalidate()
        return ok

    def load_snapshot(self) -> Dict[str, Any]:
        """``GET /v1/load`` — the same ``dstpu-load-v1`` dict the
        in-process frontend returns, cached for ``snapshot_ttl_s``
        (invalidated by every local submit/stream/migration event).
        Unreachable remotes degrade to an idle-shaped stub (placement
        already excludes them via ``driver_alive``; the stub only keeps
        racing readers safe)."""
        now = self._clock()
        with self._lock:
            if self._load_cache is not None \
                    and now - self._load_t < self.snapshot_ttl_s:
                return self._load_cache
        snap = self._get_json("/v1/load", default={
            "schema": LOAD_SCHEMA,
            "admission": {"pending": 0},
            "throughput": {"tokens_per_s": None},
            "engine_backlog_tokens": 0,
            "engine_queue_depth": 0,
            "engine_running": 0,
        })
        with self._lock:
            self._load_cache = snap
            self._load_t = now
        return snap

    def stats(self) -> Dict[str, Any]:
        return self._get_json("/v1/stats", default={
            "submitted": self.n_submitted, "unreachable": True})

    def drain_pending(self) -> List[StreamHandle]:
        """Remote admission queues drain server-side (the server's own
        driver keeps running); nothing to re-home from here."""
        return []

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop using the remote. Does NOT close the remote server —
        it has its own owner; in-flight streams are given ``timeout``
        to finish naturally."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            readers = list(self._readers)
        deadline = None if timeout is None else self._clock() + timeout
        for t in readers:
            left = None if deadline is None \
                else max(0.0, deadline - self._clock())
            t.join(left if left is not None else 5.0)

    def __enter__(self) -> "RemoteReplica":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
