"""Continuous-batching serving subsystem.

The reference ships a dedicated inference layer
(``deepspeed/inference/engine.py``); this package is its TPU-native
serving tier — slotted KV-cache management, Orca-style iteration-level
scheduling, and a two-program jit discipline. See docs/serving.md.
"""

from .kv_cache import SlotAllocator, SlotKVCacheManager  # noqa: F401
from .paged_kv import (BlockAllocator, PagedKVCacheManager,  # noqa: F401
                       PagedSlotAllocator, PrefixCache)
from .scheduler import (ContinuousBatchScheduler, Request,  # noqa: F401
                        REJECT_DEADLINE_EXPIRED, REJECT_KV_OOM,
                        REJECT_PROMPT_TOO_LONG, REJECT_QUEUE_FULL)
from .metrics import (Reservoir, ServingMetrics,  # noqa: F401
                      csv_monitor_master)
from .engine import MigrationError, ServingEngine  # noqa: F401
from .kv_tiers import KVTierManager  # noqa: F401
from .fleet import (ElasticConfig, ElasticController,  # noqa: F401
                    FleetReplica, FleetRouter, RemoteReplica,
                    ReplicaServer)
