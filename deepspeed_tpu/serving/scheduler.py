"""Continuous-batching request scheduler (Orca-style iteration-level
scheduling).

The reference inference engine serves one ``generate`` call at a time
(``deepspeed/inference/engine.py:546`` — request-level scheduling). This
scheduler makes admission decisions BETWEEN decode iterations instead:
whenever a slot frees (EOS / token budget / deadline), the next queued
request is prefilled and joins the running batch on the very next decode
step, so the decode program always runs as full as traffic allows.

Host-side only — no JAX. The engine (serving/engine.py) drives it:

    while scheduler.has_work():
        for req in scheduler.admit():        # prefill + slot insert
            ...; scheduler.record_first_token(req, tok)
        finished = scheduler.step_tokens({slot: tok, ...})      # K=1 loop
        finished = scheduler.step_tokens_chunk({slot: [t0, t1, ...], ...})
        # fused K-step loop: one host sync per chunk, same semantics

Backpressure: the queue is bounded; ``submit`` rejects with a reason
(``queue_full`` / ``prompt_too_long``) instead of buffering unboundedly —
the caller sees the rejection immediately and can shed load upstream.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

REJECT_QUEUE_FULL = "queue_full"
REJECT_PROMPT_TOO_LONG = "prompt_too_long"
REJECT_DEADLINE_EXPIRED = "deadline_expired"
REJECT_KV_OOM = "kv_blocks_exhausted"

_uid_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its lifecycle record."""
    prompt: np.ndarray                     # [prompt_len] int32 token ids
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    deadline_s: Optional[float] = None     # absolute clock() time budget
    uid: int = dataclasses.field(default_factory=lambda: next(_uid_counter))
    # distributed trace id (fleet journeys): minted at submit by the
    # frontend/router, preserved across a crash-reroute
    trace_id: Optional[str] = None
    # billing/accounting identity — admission rate-limits per tenant and
    # TraceLog aggregates per-tenant goodput under this label; direct
    # engine callers that never set one land in the "default" bucket so
    # aggregation never silently drops untagged requests
    tenant: str = "default"

    # ---- filled in by the scheduler ----
    status: str = "new"   # new|queued|running|done|expired|rejected|cancelled
    reject_reason: Optional[str] = None
    slot: Optional[int] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    submit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens, the ``generate`` output contract."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token: submit -> first sampled token."""
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t


class ContinuousBatchScheduler:
    """Bounded FIFO queue + iteration-level admission + per-request
    termination (EOS / max_new_tokens / deadline / cache-row exhaustion).

    ``allocator`` is a :class:`~deepspeed_tpu.serving.kv_cache.SlotAllocator`
    (or the manager wrapping one); ``clock`` is injectable for
    deterministic tests."""

    def __init__(self, allocator, *, max_queue: int = 64,
                 max_prompt_len: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.allocator = allocator
        self.max_queue = max_queue
        self.max_prompt_len = max_prompt_len
        self.clock = clock
        self.queue: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}          # slot -> request
        self.finished: List[Request] = []
        self.n_rejected = 0
        self.n_expired = 0
        self.n_cancelled = 0

    # ------------------------------------------------------------- submit
    def submit(self, req: Request) -> bool:
        """Enqueue, or reject-with-reason (bounded queue backpressure /
        a prompt the fixed shapes cannot serve). Returns acceptance."""
        req.submit_t = self.clock()
        limit = self.max_prompt_len
        seq_cap = getattr(self.allocator, "max_seq_len", None)
        too_long = (limit is not None and req.prompt_len > limit) or (
            seq_cap is not None
            and req.prompt_len + req.max_new_tokens > seq_cap)
        if too_long:
            return self._reject(req, REJECT_PROMPT_TOO_LONG)
        # paged allocators expose a finite token pool: a request no EMPTY
        # pool could hold can never be admitted — reject-with-reason now
        # instead of wedging the FIFO head forever
        pool_cap = getattr(self.allocator, "pool_capacity_tokens", None)
        if (pool_cap is not None
                and req.prompt_len + req.max_new_tokens > pool_cap):
            return self._reject(req, REJECT_KV_OOM)
        # an already-expired deadline can never be met: reject here rather
        # than admit, prefill, and kill at the first chunk boundary
        if req.deadline_s is not None and req.submit_t >= req.deadline_s:
            return self._reject(req, REJECT_DEADLINE_EXPIRED)
        if len(self.queue) >= self.max_queue:
            return self._reject(req, REJECT_QUEUE_FULL)
        req.status = "queued"
        self.queue.append(req)
        return True

    def _reject(self, req: Request, reason: str) -> bool:
        req.status = "rejected"
        req.reject_reason = reason
        self.n_rejected += 1
        return False

    # ---------------------------------------------------------- admission
    def admit(self, token_budget: Optional[int] = None,
              lane_cost=None) -> List[Request]:
        """FIFO admission while slots are free. Deadline-expired queued
        requests are shed here (never prefilled). Returned requests have
        ``.slot`` leased; the caller prefills, inserts into the arena, and
        reports the prefill's sampled token via ``record_first_token``.

        Fused chunked-prefill engines pass a ``token_budget`` (the chunk
        token budget's free headroom) and a ``lane_cost(req)`` callable
        (the per-scan-step cost the new lane adds — its first prompt
        chunk, or one decode token): admission stops at the first request
        that would overflow the budget, EXCEPT that an otherwise-idle
        engine always admits one (a budget must never starve an empty
        scan). Both default to None — plain slot-bound FIFO admission."""
        admitted: List[Request] = []
        budget = token_budget
        while self.queue:
            req = self.queue[0]
            if (req.deadline_s is not None
                    and self.clock() >= req.deadline_s):
                self.queue.popleft()
                self._finish(req, "expired")
                continue
            if budget is not None and lane_cost is not None:
                cost = lane_cost(req)
                if cost > budget and (self.running or admitted):
                    break
                budget -= cost
            slot = self._lease(req)
            if slot is None:
                break
            self.queue.popleft()
            req.slot = slot
            req.status = "running"
            self.running[slot] = req
            admitted.append(req)
        return admitted

    def _lease(self, req: Request) -> Optional[int]:
        """Request-shaped lease when the allocator supports it (the paged
        allocator plans block reservations / prefix sharing per request);
        plain fill-length lease otherwise (the dense slot arena)."""
        alloc_request = getattr(self.allocator, "alloc_request", None)
        if alloc_request is not None:
            return alloc_request(req)
        return self.allocator.alloc(req.prompt_len)

    # ---------------------------------------------------------- lifecycle
    def record_first_token(self, req: Request, token: int) -> None:
        """The prefill program samples token #1; a request may terminate
        right here (max_new_tokens == 1, or an immediate EOS)."""
        req.first_token_t = self.clock()
        self._append(req, token)

    def step_tokens(self, tokens_by_slot: Dict[int, int]) -> List[Request]:
        """Apply one decode iteration's sampled token per slot; returns the
        requests that finished this step (their slots are already free for
        the next admission pass)."""
        before = len(self.finished)
        for slot, token in tokens_by_slot.items():
            req = self.running.get(slot)
            if req is None:
                raise KeyError(f"no running request in slot {slot}")
            self._append(req, token)
        return self.finished[before:]

    def step_tokens_chunk(self, tokens_by_slot: Dict[int, List[int]]
                          ) -> List[Request]:
        """Apply one fused multi-step decode chunk: a SEQUENCE of sampled
        tokens per slot (serving/engine.py's device-resident K-step loop
        syncs once per chunk and hands the whole token buffer here).
        Per-token semantics are identical to K ``step_tokens`` calls for
        that slot: the allocator fill advances one row per consumed token
        (so the cache-row safety net in ``_append`` sees the same
        remaining count the per-token loop would), and consumption stops
        at the request's own termination — trailing tokens a speculative
        chunk produced past EOS/budget/deadline are dropped, never
        appended. Returns the requests finished within this chunk."""
        before = len(self.finished)
        for slot, tokens in tokens_by_slot.items():
            req = self.running.get(slot)
            if req is None:
                raise KeyError(f"no running request in slot {slot}")
            for token in tokens:
                if req.status != "running":
                    break
                self.allocator.advance([slot])
                self._append(req, token)
        return self.finished[before:]

    def _append(self, req: Request, token: int) -> None:
        req.tokens.append(int(token))
        # a non-final token must be fed back through decode (written at the
        # slot's fill position), so a row with no space left terminates the
        # request — unreachable when submit()'s length guard ran, kept as
        # the safety net for allocators without a max_seq_len
        done = (len(req.tokens) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and int(token) == req.eos_token_id)
                or (req.slot is not None
                    and self.allocator.remaining(req.slot) <= 0))
        expired = (req.deadline_s is not None
                   and self.clock() >= req.deadline_s)
        if expired and not done:
            self._finish(req, "expired")
        elif done:
            self._finish(req, "done")

    def cancel(self, req: Request) -> bool:
        """Caller-initiated termination. A queued request is removed
        before it ever prefills; a running request frees its slot for the
        very next admission pass (the engine deactivates the device lane
        at the next chunk launch). Returns False when the request is
        already terminal (or was never submitted here)."""
        if req.status == "queued":
            # identity scan, not deque.remove: the dataclass __eq__
            # compares the numpy prompt arrays, which raises on bool()
            for i, queued in enumerate(self.queue):
                if queued is req:
                    del self.queue[i]
                    self._finish(req, "cancelled")
                    return True
            return False
        if req.status == "running" and self.running.get(req.slot) is req:
            self._finish(req, "cancelled")
            return True
        return False

    def _finish(self, req: Request, status: str) -> None:
        req.status = status
        req.finish_t = self.clock()
        if status == "expired":
            self.n_expired += 1
        elif status == "cancelled":
            self.n_cancelled += 1
        if req.slot is not None:
            self.running.pop(req.slot, None)
            self.allocator.free(req.slot)
        self.finished.append(req)

    # ------------------------------------------------------------ queries
    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def has_work(self) -> bool:
        return bool(self.queue or self.running)
