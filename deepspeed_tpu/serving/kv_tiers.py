"""Tiered KV cache: HBM block pool → pinned host DRAM → NVMe spill.

The paged allocator treats HBM as a hard wall: ``_ensure_free`` EVICTS
cold prefix-cache entries (serving/paged_kv.py) and their KV is gone — a
repeated prompt re-prefills from scratch. This module is the DeepSpeed
swap_tensor / ZeRO-Infinity NVMe-tier design reborn behind the paged
allocator: eviction becomes DEMOTION. A cold prefix entry's blocks are
gathered off-device into host DRAM; when the DRAM tier overflows its
watermark, the coldest entries spill to NVMe files through the
``ops/aio.py`` heritage path (``AsyncIOHandle`` — the ``csrc/aio``
analogue). A later request for the same prompt PROMOTES the entry back:
the fetch + decode runs on a background worker thread, overlapped
against the engine's double-buffered chunk launches, and the engine
installs completed promotions at its next admission pass — re-admission
never blocks the decode scan.

Serialization is PR 15's migration codec
(:func:`~deepspeed_tpu.serving.fleet.transport.encode_bundle`): a
demoted block and a migrated block are the same bytes. That makes the
DRAM tier double as a *distributed* prefix cache — a peer replica
fetches a neighbor's demoted prefix over ``GET /v1/prefix?fetch=1``
(:meth:`KVTierManager.fetch_bundle` / :meth:`install_bundle`) instead
of re-prefilling.

Thread model (the invariants the race tests pin down):
  * the DEVICE pool is touched only by the engine thread — demotion
    gathers happen inside the prefix cache's eviction hook (engine
    thread), promotion scatters happen in ``ServingEngine._admit``'s
    drain of :meth:`drain_ready` (engine thread);
  * the tier maps are host-side numpy behind one map lock — transport
    threads may probe/fetch/install concurrently with the worker and
    the engine;
  * every use of the shared ``AsyncIOHandle`` is serialized behind a
    dedicated I/O mutex (separate from the map lock): the handle's
    pending-op/fd lists are not thread-safe and ``wait()`` drains and
    closes EVERYTHING in flight, so an unserialized spill racing an
    unspill could complete the other thread's ops and hand back an
    uninitialized read buffer;
  * NVMe reads AND writes run with the map lock DROPPED (only the I/O
    mutex held) so neither a peer fetch of a spilled entry nor a
    watermark spill ever stalls the engine thread's holds()/admit
    path. A spill-in-progress entry parks in ``_spilling`` (in-memory,
    claimable): ``holds()`` keeps answering True, a promotion or peer
    fetch can claim/serve the payload straight from memory, and the
    writer detects the claim when it re-acquires the map lock and
    discards its now-orphaned file. Spilled-entry reads pin the file
    so a concurrent promotion defers its unlink until the pin
    releases;
  * a promotion in flight keeps the entry OUT of the tier maps (no
    double-promote) but :meth:`holds` still answers True so the
    allocator keeps deferring the request until the payload lands.

Host-only: imports no JAX.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue
import shutil
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis import locks
from ..ops.aio import AsyncIOHandle

# schema tag stamped on every report() / wire bundle this module emits,
# versioned like dstpu-tenants/dstpu-migrate so readers can gate on shape
TIERS_SCHEMA = "dstpu-tiers-v1"
PREFIX_FETCH_SCHEMA = "dstpu-prefix-v1"

_spill_seq = itertools.count()


@dataclasses.dataclass
class _DramEntry:
    prompt_len: int
    first_token: int
    leaves: Dict[str, np.ndarray]    # normalized leaf key -> blocks array
    nbytes: int


@dataclasses.dataclass
class _NvmeEntry:
    prompt_len: int
    first_token: int
    path: str
    # per-leaf (key, dtype, shape, nbytes) in file order — the file is
    # the concatenated raw bytes; dtype objects (not strings) so
    # ml_dtypes kinds like bfloat16 round-trip exactly
    meta: List[Tuple[str, Any, Tuple[int, ...], int]]
    nbytes: int


def _leaves_nbytes(leaves: Dict[str, np.ndarray]) -> int:
    return sum(int(a.nbytes) for a in leaves.values())


class KVTierManager:
    """Host-side demotion/promotion ladder for prefix-cache entries.

    ``dram_bytes`` is the DRAM tier's high watermark: admissions past it
    spill the coldest entries to NVMe. ``nvme_bytes`` caps the spill
    tier; past it the coldest spill files are dropped (the data is then
    gone — the request re-prefills, exactly the pre-tier behavior, so
    the ladder degrades to the old eviction semantics under unbounded
    pressure). ``spill_dir`` defaults to a private tempdir removed by
    :meth:`close`."""

    def __init__(self, *, dram_bytes: int = 256 << 20,
                 nvme_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 aio: Optional[AsyncIOHandle] = None):
        if dram_bytes < 0:
            raise ValueError(f"dram_bytes must be >= 0, got {dram_bytes}")
        self.dram_capacity = int(dram_bytes)
        self.nvme_capacity = None if nvme_bytes is None else int(nvme_bytes)
        self._own_spill_dir = spill_dir is None
        self._spill_dir = spill_dir
        self._aio = aio if aio is not None else AsyncIOHandle()
        self._lock = locks.make_rlock("kv_tiers.map")
        # the shared AsyncIOHandle is NOT thread-safe (wait() drains and
        # closes every op/fd in flight, whoever submitted it): all aio
        # use — spill writes and unspill reads, from any thread — runs
        # under this mutex, which nests INSIDE the map lock (never take
        # the map lock while holding it)
        self._io_lock = locks.make_lock("kv_tiers.io")
        self._dram: "OrderedDict[bytes, _DramEntry]" = OrderedDict()
        self._nvme: "OrderedDict[bytes, _NvmeEntry]" = OrderedDict()
        # entries mid-spill: still in host memory, owned by the thread
        # writing them out with the map lock DROPPED. holds() counts
        # them; promotions/fetches may claim/serve them from memory —
        # the writer notices the claim at finalize and drops its file.
        self._spilling: Dict[bytes, _DramEntry] = {}
        # spill files a peer fetch is reading with the map lock dropped:
        # key -> reader count; an unlink that lands mid-read parks in
        # _unlink_deferred and the last unpin performs it
        self._pins: Dict[bytes, int] = {}
        self._unlink_deferred: Dict[bytes, str] = {}
        self._inflight: Dict[bytes, float] = {}   # key -> request clock
        self._ready: "OrderedDict[bytes, _DramEntry]" = OrderedDict()
        self._queue: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._closed = False
        # counters (report() exports; engine mirrors as serve/tier_*)
        self.demotions_dram = 0      # HBM -> DRAM admits
        self.demotions_nvme = 0      # DRAM -> NVMe spills
        self.promotions_dram = 0     # DRAM -> HBM completions
        self.promotions_nvme = 0     # NVMe -> HBM completions
        self.dropped = 0             # capacity drops (data lost)
        self.promote_failures = 0
        self.peer_fetches = 0        # bundles served to peers
        self.peer_installs = 0       # bundles installed from peers
        self._promote_wait_s: deque = deque(maxlen=512)
        self._worker = threading.Thread(
            target=self._worker_loop, name="kv-tier-promote", daemon=True)
        self._worker.start()

    # ---------------------------------------------------------- demotion
    def admit(self, key: bytes, prompt_len: int, first_token: int,
              leaves: Dict[str, np.ndarray]) -> bool:
        """Admit a demoted prefix entry into the DRAM tier (called from
        the prefix cache's eviction hook — engine thread — or from
        :meth:`install_bundle` — transport thread). Overflow cascades:
        coldest DRAM entries spill to NVMe, coldest NVMe entries drop.

        The spill WRITES run with the map lock dropped (lockcheck:
        file IO under the map lock would stall holds()/fetch on every
        other thread behind a disk write): overflow entries are parked
        in ``_spilling`` under the lock, written out lock-free, and
        published to the NVMe map — or discarded, if a concurrent
        promotion claimed the in-memory payload mid-write — when the
        writer re-acquires the lock."""
        to_spill: List[Tuple[bytes, _DramEntry]] = []
        with self._lock:
            if self._closed:
                return False
            if (key in self._dram or key in self._nvme
                    or key in self._inflight or key in self._ready
                    or key in self._spilling):
                return False                 # already tiered somewhere
            leaves = {k: np.ascontiguousarray(a)
                      for k, a in leaves.items()}
            entry = _DramEntry(int(prompt_len), int(first_token), leaves,
                               _leaves_nbytes(leaves))
            if entry.nbytes > self.dram_capacity:
                # an entry no empty DRAM tier could hold goes straight
                # to NVMe (or drops if that is also too small)
                if self.nvme_capacity is not None \
                        and entry.nbytes > self.nvme_capacity:
                    self.dropped += 1
                    return False
                self._spilling[key] = entry
                to_spill.append((key, entry))
            else:
                self._dram[key] = entry
            self.demotions_dram += 1
            to_spill.extend(self._collect_overflow_locked())
        admitted = True
        for k, e in to_spill:
            survived = self._spill(k, e)
            if k == key:
                admitted = survived
        if to_spill:
            self._enforce_nvme_watermark()
        return admitted

    def _collect_overflow_locked(self) -> List[Tuple[bytes, _DramEntry]]:
        """Pop DRAM overflow (coldest first) into the ``_spilling`` map.
        Caller holds the map lock and performs the writes AFTER dropping
        it; entries too big for the NVMe cap drop here."""
        out: List[Tuple[bytes, _DramEntry]] = []
        while self.dram_bytes > self.dram_capacity and self._dram:
            k, e = self._dram.popitem(last=False)
            if self.nvme_capacity is not None \
                    and e.nbytes > self.nvme_capacity:
                self.dropped += 1
                continue
            self._spilling[k] = e
            out.append((k, e))
        return out

    def _enforce_nvme_watermark(self) -> None:
        with self._lock:
            while (self.nvme_capacity is not None
                   and self.nvme_bytes > self.nvme_capacity
                   and self._nvme):
                key, spilled = self._nvme.popitem(last=False)
                self._unlink_entry(key, spilled.path)
                self.dropped += 1

    def _spill(self, key: bytes, entry: _DramEntry) -> bool:
        """DRAM -> NVMe for an entry parked in ``_spilling``: one spill
        file per entry, the leaves' raw bytes concatenated in sorted-key
        order, written through the aio handle. Runs with the map lock
        DROPPED — only the I/O mutex guards the write. Returns True if
        the payload survives: published to the NVMe map, or claimed out
        of ``_spilling`` by a concurrent promotion/close mid-write (the
        file is then an orphan and is unlinked here)."""
        path = os.path.join(self.spill_dir,
                            f"prefix-{next(_spill_seq):08d}.kv")
        meta: List[Tuple[str, Any, Tuple[int, ...], int]] = []
        offset = 0
        failed = False
        try:
            with self._io_lock:
                for name in sorted(entry.leaves):
                    a = entry.leaves[name]
                    flat = np.ascontiguousarray(a) \
                        .view(np.uint8).reshape(-1)
                    self._aio.async_pwrite(flat, path, offset)
                    meta.append((name, a.dtype, tuple(a.shape),
                                 int(a.nbytes)))
                    offset += int(a.nbytes)
                self._aio.wait()
        except OSError:
            failed = True
        with self._lock:
            still_ours = self._spilling.pop(key, None) is not None
            if still_ours and not failed:
                self._nvme[key] = _NvmeEntry(
                    entry.prompt_len, entry.first_token, path, meta,
                    entry.nbytes)
                self.demotions_nvme += 1
                return True
            if still_ours:       # write failed with the data unclaimed
                self.dropped += 1
        self._unlink(path)       # failed write, or orphaned by a claim
        return not still_ours

    def _unspill(self, spilled: _NvmeEntry) -> _DramEntry:
        """NVMe -> host numpy. Runs WITHOUT the map lock (worker or
        transport thread) — only the I/O mutex, so a disk read never
        blocks holds()/admit, and a concurrent spill cannot have its
        pending aio ops drained by this read's wait()."""
        leaves: Dict[str, np.ndarray] = {}
        offset = 0
        with self._io_lock:
            for name, dtype, shape, nbytes in spilled.meta:
                buf = np.empty(nbytes, np.uint8)
                self._aio.async_pread(buf, spilled.path, offset)
                self._aio.wait()
                leaves[name] = buf.view(dtype).reshape(shape)
                offset += nbytes
        return _DramEntry(spilled.prompt_len, spilled.first_token, leaves,
                          spilled.nbytes)

    def _unlink(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _unlink_entry(self, key: bytes, path: str) -> None:
        """Unlink ``key``'s spill file — or defer while a peer fetch is
        mid-read on it (caller holds the map lock; the reader's unpin
        performs the deferred unlink)."""
        if self._pins.get(key):
            self._unlink_deferred[key] = path
        else:
            self._unlink(path)

    def _unpin_locked(self, key: bytes) -> None:
        n = self._pins.get(key, 0) - 1
        if n > 0:
            self._pins[key] = n
            return
        self._pins.pop(key, None)
        path = self._unlink_deferred.pop(key, None)
        if path is not None:
            self._unlink(path)

    # --------------------------------------------------------- promotion
    def holds(self, key: bytes) -> bool:
        """Membership across every tier INCLUDING promotions in flight /
        ready — the allocator defers a request while this is True, so an
        entry mid-promotion must keep answering."""
        with self._lock:
            return (key in self._dram or key in self._nvme
                    or key in self._inflight or key in self._ready
                    or key in self._spilling)

    def request_promotion(self, key: bytes) -> bool:
        """Queue an async promotion (engine thread; returns immediately).
        The worker moves the payload to host numpy; the engine drains
        completions via :meth:`drain_ready` at its next admission pass."""
        with self._lock:
            if self._closed or key in self._inflight or key in self._ready:
                return False
            if key not in self._dram and key not in self._nvme \
                    and key not in self._spilling:
                return False
            self._inflight[key] = time.monotonic()
        self._queue.put(key)
        return True

    def drain_ready(self) -> List[Tuple[bytes, int, int,
                                        Dict[str, np.ndarray]]]:
        """Pop every completed promotion: ``[(key, prompt_len,
        first_token, leaves), ...]``. Engine thread only — the caller
        scatters the leaves back into the device pool and republishes
        the prefix-cache entry."""
        out = []
        with self._lock:
            while self._ready:
                key, entry = self._ready.popitem(last=False)
                out.append((key, entry.prompt_len, entry.first_token,
                            entry.leaves))
        return out

    def _worker_loop(self) -> None:
        while True:
            key = self._queue.get()
            if key is None:
                return
            try:
                self._promote_one(key)
            except Exception:
                # a failed promotion must not wedge the allocator's
                # deferral loop: drop every trace of the key so holds()
                # flips False and the request re-prefills as a miss
                # (_promote_one already unlinked the spill file it had
                # popped; this pop only covers a key never reached)
                with self._lock:
                    self._inflight.pop(key, None)
                    self._dram.pop(key, None)
                    self._spilling.pop(key, None)
                    spilled = self._nvme.pop(key, None)
                    if spilled is not None:
                        self._unlink_entry(key, spilled.path)
                    self.promote_failures += 1

    def _promote_one(self, key: bytes) -> None:
        with self._lock:
            t0 = self._inflight.get(key)
            entry = self._dram.pop(key, None)
            if entry is None:
                # claim a mid-spill payload straight from memory — the
                # writer sees the claim at finalize and drops its file
                entry = self._spilling.pop(key, None)
            spilled = None if entry is not None \
                else self._nvme.pop(key, None)
        if entry is None and spilled is None:
            with self._lock:
                self._inflight.pop(key, None)
            return
        from_nvme = entry is None
        if from_nvme:
            try:
                entry = self._unspill(spilled)
            except BaseException:
                # the entry is already popped from _nvme: unlink its
                # file here or it leaks — the worker's failure handler
                # can no longer find it
                with self._lock:
                    self._unlink_entry(key, spilled.path)
                raise
            with self._lock:
                self._unlink_entry(key, spilled.path)
        with self._lock:
            self._ready[key] = entry
            self._inflight.pop(key, None)
            if from_nvme:
                self.promotions_nvme += 1
            else:
                self.promotions_dram += 1
            if t0 is not None:
                self._promote_wait_s.append(time.monotonic() - t0)

    def abandon_ready(self, key: bytes, entry_fields: Tuple[int, int,
                      Dict[str, np.ndarray]]) -> None:
        """Return a drained promotion the engine could NOT install (the
        pool had no free blocks): the payload goes back to the DRAM tier
        so a later, less-pressured pump can retry — nothing is lost."""
        prompt_len, first_token, leaves = entry_fields
        self.admit(key, prompt_len, first_token, leaves)

    # ------------------------------------------------------- fleet fetch
    def fetch_bundle(self, key: bytes) -> Optional[Dict[str, Any]]:
        """Serve a peer's prefix fetch (transport thread): the entry's
        payload in the migrate-bundle shape ``encode_bundle`` speaks.
        Non-destructive — the local tier keeps its copy (the peer's
        fetch must not evict the home replica's warm state). A spilled
        entry's NVMe read runs with the map lock DROPPED (the engine
        thread's holds()/admit path must never wait on a disk read);
        the pin keeps a concurrent promotion from unlinking the file
        mid-read."""
        payload = None
        spilled = None
        with self._lock:
            entry = self._dram.get(key)
            if entry is not None:
                self._dram.move_to_end(key)
            else:
                # a mid-spill entry's payload is still in host memory —
                # serve it from there (non-destructively: the writer
                # keeps publishing it to NVMe)
                entry = self._spilling.get(key)
                if entry is None:
                    entry = self._ready.get(key)
            if entry is not None:
                payload = (dict(entry.leaves), entry.prompt_len,
                           entry.first_token)
            else:
                spilled = self._nvme.get(key)
                if spilled is None:
                    return None
                self._pins[key] = self._pins.get(key, 0) + 1
        if payload is None:
            try:
                entry = self._unspill(spilled)
                payload = (entry.leaves, entry.prompt_len,
                           entry.first_token)
            except OSError:
                pass
            finally:
                with self._lock:
                    self._unpin_locked(key)
            if payload is None:
                # the file vanished mid-read (close(), or a capacity
                # drop racing the pin): the payload may have landed in
                # an in-memory tier via a concurrent promotion — retry
                # those once before reporting a miss
                with self._lock:
                    entry = (self._dram.get(key) or self._ready.get(key)
                             or self._spilling.get(key))
                    if entry is None:
                        return None
                    payload = (dict(entry.leaves), entry.prompt_len,
                               entry.first_token)
        leaves, pl_, ft = payload
        with self._lock:
            self.peer_fetches += 1
        return {"schema": PREFIX_FETCH_SCHEMA, "key": key.hex(),
                "prompt_len": int(pl_), "first_token": int(ft),
                "kv": leaves}

    def install_bundle(self, bundle: Dict[str, Any]) -> bool:
        """Install a peer-fetched prefix bundle into the DRAM tier
        (transport thread; no device access — the entry promotes through
        the normal async path when a request for it arrives)."""
        if bundle.get("schema") != PREFIX_FETCH_SCHEMA:
            raise ValueError(
                f"unsupported prefix bundle schema {bundle.get('schema')!r}"
                f" (want {PREFIX_FETCH_SCHEMA})")
        key = bytes.fromhex(bundle["key"])
        leaves = {k: np.asarray(v) for k, v in bundle["kv"].items()}
        ok = self.admit(key, int(bundle["prompt_len"]),
                        int(bundle["first_token"]), leaves)
        if ok:
            with self._lock:
                self.peer_installs += 1
        return ok

    # --------------------------------------------------------- accounting
    @property
    def dram_bytes(self) -> int:
        return sum(e.nbytes for e in self._dram.values()) \
            + sum(e.nbytes for e in self._ready.values())

    @property
    def nvme_bytes(self) -> int:
        return sum(e.nbytes for e in self._nvme.values())

    @property
    def spill_dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="dstpu-kv-tier-")
        return self._spill_dir

    def spill_files(self) -> List[str]:
        with self._lock:
            return [e.path for e in self._nvme.values()]

    def _promote_wait_pct(self, q: float) -> float:
        with self._lock:
            waits = sorted(self._promote_wait_s)
        if not waits:
            return 0.0
        i = min(int(q * len(waits)), len(waits) - 1)
        return waits[i]

    def report(self) -> Dict[str, Any]:
        """Per-tier accounting merged into ``arena_report()`` and
        exported as ``serve/tier_*`` gauges — schema-versioned like the
        dstpu-tenants blocks so dashboards can gate on shape."""
        with self._lock:
            return {
                "schema": TIERS_SCHEMA,
                "dram_entries": (len(self._dram) + len(self._ready)
                                 + len(self._spilling)),
                "dram_bytes": self.dram_bytes,
                "dram_capacity_bytes": self.dram_capacity,
                "nvme_entries": len(self._nvme),
                "nvme_bytes": self.nvme_bytes,
                "nvme_capacity_bytes": self.nvme_capacity,
                "spill_files": len(self._nvme),
                "inflight_promotions": len(self._inflight),
                "demotions_dram": self.demotions_dram,
                "demotions_nvme": self.demotions_nvme,
                "promotions_dram": self.promotions_dram,
                "promotions_nvme": self.promotions_nvme,
                "promote_failures": self.promote_failures,
                "dropped": self.dropped,
                "peer_fetches": self.peer_fetches,
                "peer_installs": self.peer_installs,
                "promote_wait_p50_s": self._promote_wait_pct(0.50),
                "promote_wait_p99_s": self._promote_wait_pct(0.99),
            }

    # ------------------------------------------------------------ closing
    def close(self) -> None:
        """Stop the worker and remove every spill file (and the private
        spill dir). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._queue.put(None)
        self._worker.join(timeout=5.0)
        with self._lock:
            for key, spilled in self._nvme.items():
                self._unlink_entry(key, spilled.path)
            self._nvme.clear()
            self._dram.clear()
            self._ready.clear()
            self._inflight.clear()
            # in-flight spill writers see their claim vanish at
            # finalize and unlink their own orphaned files
            self._spilling.clear()
        if self._own_spill_dir and self._spill_dir is not None:
            shutil.rmtree(self._spill_dir, ignore_errors=True)
            self._spill_dir = None

    def __enter__(self) -> "KVTierManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            # best-effort spill-dir cleanup at GC: the map RLock is
            # reentrant and close() is idempotent, so a same-thread GC
            # cannot self-deadlock; a cross-thread holder delays, never
            # wedges, this finalizer
            # lockcheck: disable=lock-in-finalizer
            self.close()
        except Exception:
            pass
