"""Live serving metrics, emitted through the monitor fan-out.

Events ride the existing ``(label, value, sample)`` contract of
``deepspeed_tpu/monitor/monitor.py`` (reference monitor/monitor.py:45), so
any configured writer — CSV, TensorBoard, W&B — picks them up unchanged.
``sample`` is the decode-iteration counter: serving dashboards line up
against the same x-axis the training monitor uses for steps.

Labels:
  serving/tokens_per_s      aggregate decode throughput since start
  serving/ttft_s            mean time-to-first-token over finished requests
  serving/ttft_p50_s        reservoir-sampled TTFT percentiles (p50/p95/
  serving/ttft_p95_s        p99) — tail latency, the number SLOs are
  serving/ttft_p99_s        written against; the mean stays for dashboards
  serving/queue_depth       requests waiting for a slot
  serving/slot_occupancy    fraction of KV slots leased [0, 1]
  serving/requests_done     completed requests (cumulative)
  serving/rejected_total    backpressure rejections (cumulative)
  serving/prefill_padding_waste
                            fraction of prefill compute spent on bucket
                            padding: 1 - true_prompt_tokens/padded_tokens
                            (0 when every prompt exactly fills its bucket)
  serving/prefill_programs  distinct compiled (batch, bucket) prefill
                            program shapes so far (the compile-cache cost
                            of bucketed prefill, watched so it stays
                            bounded)
  serving/prefix_cache_hits admissions served from the paged prefix cache
                            (prefill skipped; blocks shared COW)
  serving/prefix_cache_misses
                            paged admissions that ran a real prefill
                            (0 for both in dense mode)
  serving/prefix_hit_rate   hits / (hits + misses), 0.0 before the first
                            paged admission
  serving/cow_forks         copy-on-write block forks (a shared partial
                            block privatized for one request)
"""

from __future__ import annotations

import random
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence


class Reservoir:
    """Fixed-size uniform reservoir (Vitter's algorithm R) for streaming
    percentile estimates. Under ``capacity`` observations the percentiles
    are EXACT; past it each seen value has equal probability of being in
    the sample, so long-running servers keep an unbiased tail estimate in
    O(capacity) memory. Host-side only; seeded so runs are
    reproducible."""

    def __init__(self, capacity: int = 1024, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._rng = random.Random(seed)
        self.values: List[float] = []
        self.n_seen = 0
        self.total = 0.0        # running sum over ALL seen (not the sample)

    def add(self, x: float) -> None:
        self.n_seen += 1
        self.total += float(x)
        if len(self.values) < self.capacity:
            self.values.append(float(x))
        else:
            j = self._rng.randrange(self.n_seen)
            if j < self.capacity:
                self.values[j] = float(x)

    def percentile(self, q: float) -> float:
        """Linear-interpolated percentile over the sample, q in [0, 100]
        (out-of-range q is clamped, never an index error); 0.0 when
        empty (matches the mean-TTFT zero default)."""
        if not self.values:
            return 0.0
        xs = sorted(self.values)
        if len(xs) == 1:
            return xs[0]
        q = min(100.0, max(0.0, float(q)))
        pos = (q / 100.0) * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (pos - lo) * (xs[hi] - xs[lo])

    def percentiles(self, qs: Sequence[float] = (50, 95, 99)
                    ) -> Dict[float, float]:
        return {q: self.percentile(q) for q in qs}


def csv_monitor_master(output_path: str, job_name: str = "serving"):
    """A CSV-only MonitorMaster for serving/benchmark runs that have no
    DeepSpeedConfig — same writer class, same on-disk format."""
    from ..monitor.monitor import MonitorMaster
    cfg = SimpleNamespace(
        tensorboard=SimpleNamespace(enabled=False),
        wandb=SimpleNamespace(enabled=False),
        csv_monitor=SimpleNamespace(enabled=True, output_path=output_path,
                                    job_name=job_name))
    return MonitorMaster(cfg)


class ServingMetrics:
    """Aggregates serving counters and periodically flushes them as monitor
    events. ``clock`` is injectable for deterministic tests."""

    def __init__(self, monitor=None, *, emit_every_steps: int = 16,
                 clock=time.perf_counter):
        self.monitor = monitor
        self.emit_every_steps = max(1, int(emit_every_steps))
        self.clock = clock
        self.t0: Optional[float] = None
        self.tokens_out = 0
        self.decode_steps = 0
        self.requests_done = 0
        self.rejected = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0
        self.ttft_reservoir = Reservoir()
        self.prefill_prompt_tokens = 0
        self.prefill_padded_tokens = 0
        self.prefill_programs = 0
        self.n_prefix_hits = 0
        self.n_prefix_misses = 0
        self.n_cow_forks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

    # ----------------------------------------------------------- recording
    def start(self) -> None:
        if self.t0 is None:
            self.t0 = self.clock()

    def on_tokens(self, n: int) -> None:
        self.tokens_out += int(n)

    def on_decode_step(self) -> None:
        self.decode_steps += 1

    def on_finished(self, requests) -> None:
        for req in requests:
            self.requests_done += 1
            if req.ttft_s is not None:
                self._ttft_sum += req.ttft_s
                self._ttft_n += 1
                self.ttft_reservoir.add(req.ttft_s)

    def on_rejected(self, n: int = 1) -> None:
        self.rejected += int(n)

    def on_prefill(self, n_prompts: int, bucket_len: int,
                   prompt_tokens: int, n_programs: int) -> None:
        """One batched bucketed prefill: ``n_prompts`` prompts padded to
        ``bucket_len`` (``prompt_tokens`` true tokens among them);
        ``n_programs`` is the engine's running count of distinct compiled
        (batch, bucket) prefill shapes."""
        self.prefill_prompt_tokens += int(prompt_tokens)
        self.prefill_padded_tokens += int(n_prompts) * int(bucket_len)
        self.prefill_programs = int(n_programs)

    def on_prefix(self, hit: bool) -> None:
        """One paged admission resolved against the prefix cache."""
        if hit:
            self.n_prefix_hits += 1
        else:
            self.n_prefix_misses += 1

    def on_cow(self) -> None:
        """One copy-on-write block fork (shared tail privatized)."""
        self.n_cow_forks += 1

    def on_spec(self, proposed: int, accepted: int) -> None:
        """One speculative chunk consumed: ``proposed`` draft tokens
        offered to verification, ``accepted`` of them kept."""
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)

    # ------------------------------------------------------------ reading
    @property
    def padding_waste(self) -> float:
        """Fraction of padded prefill positions that carried no prompt
        token (0.0 before the first prefill)."""
        if not self.prefill_padded_tokens:
            return 0.0
        return 1.0 - self.prefill_prompt_tokens / self.prefill_padded_tokens

    @property
    def mean_ttft_s(self) -> float:
        return self._ttft_sum / self._ttft_n if self._ttft_n else 0.0

    def tokens_per_s(self) -> float:
        if self.t0 is None:
            return 0.0
        dt = self.clock() - self.t0
        return self.tokens_out / dt if dt > 0 else 0.0

    @property
    def prefix_hit_rate(self) -> float:
        n = self.n_prefix_hits + self.n_prefix_misses
        return self.n_prefix_hits / n if n else 0.0

    @property
    def spec_acceptance_rate(self) -> float:
        """Accepted / proposed draft tokens (0.0 before any speculative
        chunk ran) — the lever behind speculative speedup: per-step
        emitted tokens average 1 + rate * k."""
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0)

    def snapshot(self, queue_depth: int, occupancy: float) -> Dict[str, float]:
        pct = self.ttft_reservoir.percentiles((50, 95, 99))
        return {
            "serving/tokens_per_s": self.tokens_per_s(),
            "serving/ttft_s": self.mean_ttft_s,
            "serving/ttft_p50_s": pct[50],
            "serving/ttft_p95_s": pct[95],
            "serving/ttft_p99_s": pct[99],
            "serving/queue_depth": float(queue_depth),
            "serving/slot_occupancy": float(occupancy),
            "serving/requests_done": float(self.requests_done),
            "serving/rejected_total": float(self.rejected),
            "serving/prefill_padding_waste": float(self.padding_waste),
            "serving/prefill_programs": float(self.prefill_programs),
            "serving/prefix_cache_hits": float(self.n_prefix_hits),
            "serving/prefix_cache_misses": float(self.n_prefix_misses),
            "serving/prefix_hit_rate": float(self.prefix_hit_rate),
            "serving/cow_forks": float(self.n_cow_forks),
            "serving/spec_acceptance_rate": float(self.spec_acceptance_rate),
        }

    # ------------------------------------------------------------ emitting
    def maybe_emit(self, queue_depth: int, occupancy: float,
                   force: bool = False) -> Optional[Dict[str, float]]:
        """Write a snapshot through the monitor every ``emit_every_steps``
        decode iterations (always on ``force`` — the drain path, so short
        benchmark runs still land their last rows)."""
        if not force and self.decode_steps % self.emit_every_steps != 0:
            return None
        snap = self.snapshot(queue_depth, occupancy)
        if self.monitor is not None:
            events = [(label, value, self.decode_steps)
                      for label, value in snap.items()]
            self.monitor.write_events(events)
            if force:
                self.monitor.flush()
        return snap
