"""The ONE sampling policy for serving: filter + draw, shared everywhere.

``filter_logits``/``sample_tokens`` used to live in serving/engine.py with
serving/speculative.py importing across — one reference, two call sites.
The fused Pallas epilogue (ops/pallas/sampling.py) adds a third consumer,
so the policy now lives here and CANNOT drift: the engine's sampler, the
speculative verifier's acceptance math (rejection resamples draw from the
SAME filtered distribution), and the megakernel epilogue all share this
module. engine.py re-exports both names for API stability.

``fused_filter_logits``/``fused_sample_tokens`` are the megakernel
routers: they run the sort-free Pallas kernel when the shape supports it
and fall back to the reference otherwise. Greedy draws are bit-identical
either way (the megakernel correctness contract); temperature > 0 draws
are distributionally identical but consume the rng as Gumbel noise
instead of ``jax.random.categorical``'s internal stream.
"""

from __future__ import annotations

from typing import Optional


def filter_logits(logits, temperature: float, top_k: Optional[int],
                  top_p: Optional[float] = None):
    """Temperature / top-k / nucleus (top-p) filtering over [..., V]
    logits, in f32. The filtered logits DEFINE the sampling distribution:
    ``sample_tokens`` draws ``categorical(filter_logits(...))``, and the
    speculative verifier (serving/speculative.verify_rejection) softmaxes
    the same function — acceptance math matches the sampler exactly
    because they share this code.

    Every temperature != 0 takes the same path (x / 1.0 is the bitwise
    identity, so temperature=1.0 no longer skips the scaling branch — the
    old ``not in (0.0, 1.0)`` guard forked the code path for no numeric
    effect). top-p keeps the smallest set of tokens whose cumulative
    probability reaches ``top_p`` (the argmax token always survives);
    applied after top-k when both are set."""
    import jax
    import jax.numpy as jnp
    logits = logits.astype(jnp.float32)
    if temperature != 0.0:
        logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e10, logits)
    if top_p is not None:
        srt = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep token i while the mass BEFORE it is < top_p: the first
        # token is always kept, and the set is the minimal one covering p
        keep = (cum - probs) < top_p
        kth = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                      keepdims=True)
        logits = jnp.where(logits < kth, -1e10, logits)
    return logits


def sample_tokens(logits, rng, temperature: float, top_k: Optional[int],
                  top_p: Optional[float] = None):
    """Greedy / temperature / top-k / top-p sampling over [b, V] logits —
    the same policy as InferenceEngine.generate's sampler."""
    import jax
    import jax.numpy as jnp
    logits = filter_logits(logits, temperature, top_k, top_p)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def fused_filter_logits(logits, temperature: float, top_k: Optional[int],
                        top_p: Optional[float] = None):
    """filter_logits through the sort-free Pallas kernel when the vocab
    shape supports it, reference otherwise. Accepts [..., V]; the kernel
    sees rows."""
    import jax.numpy as jnp
    from ..ops.pallas.sampling import (sampling_supported,
                                       threshold_filter_logits)
    shape = logits.shape
    rows = 1
    for dim in shape[:-1]:
        rows *= dim
    if not sampling_supported(rows, shape[-1]):
        return filter_logits(logits, temperature, top_k, top_p)
    out = threshold_filter_logits(logits.reshape(rows, shape[-1])
                                  .astype(jnp.float32),
                                  temperature, top_k, top_p)
    return out.reshape(shape)


def fused_sample_tokens(logits, rng, temperature: float,
                        top_k: Optional[int],
                        top_p: Optional[float] = None):
    """sample_tokens through the fused Pallas epilogue when supported
    (greedy stays bit-identical; temperature > 0 becomes Gumbel-max),
    reference otherwise."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas.sampling import fused_sample, sampling_supported
    b, v = logits.shape
    if not sampling_supported(b, v):
        return sample_tokens(logits, rng, temperature, top_k, top_p)
    gumbel = None
    if temperature != 0.0:
        gumbel = jax.random.gumbel(rng, (b, v), jnp.float32)
    return fused_sample(logits.astype(jnp.float32), gumbel, temperature,
                        top_k, top_p).astype(jnp.int32)
