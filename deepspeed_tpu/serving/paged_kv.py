"""Paged KV cache: block tables, copy-on-write forking, prefix sharing.

The slotted arena (serving/kv_cache.py) pins ``max_seq_len`` KV positions
per slot for every request: a short request strands the tail of its lane,
and identical prefixes (system prompts, few-shot templates) are prefilled
and stored once PER REQUEST. This module is the vLLM-PagedAttention /
SGLang-RadixAttention shape specialized to this engine's constraints:

  * the KV arena becomes a pool of fixed-size blocks
    ``[num_blocks, block_size, h*d]`` per layer, and each slot holds a
    BLOCK TABLE (``[T]`` int32 per slot, ``T = max_seq_len//block_size``)
    threaded through the decode program as a device array — the model's
    ``_kv_write_paged`` scatters through it, the paged attention op
    gathers through it;
  * blocks are refcounted: a prefix-cache entry and any number of live
    requests may reference the same block read-only; the first writer
    copies (COW) — one jitted block-copy program per fork;
  * a prefix cache keyed on the prompt token bytes makes a repeated
    prompt skip prefill entirely: its full blocks are shared by
    refcount-bump, its partial tail block is COW-forked, and the stored
    first sampled token (greedy-deterministic) seeds decode.

Allocation policy is UPFRONT RESERVATION: a request leases
``ceil((prompt_len + max_new_tokens)/block_size)`` blocks at admission or
is not admitted (FIFO head-of-line wait; ``REJECT_KV_OOM`` at submit for
requests no empty pool could ever hold). No preemption, no swapping —
a leased request always runs to termination, which keeps the scheduler's
fill/remaining arithmetic identical to the dense arena's.

Safety invariants (the reasoning the tests pin down):
  * blocks referenced by the prefix cache (refcount >= 1) are never on
    the free list, so a planned COW source cannot be re-leased between
    planning and the device copy — hit plans additionally hold a
    temporary refcount on the COW source across same-batch evictions;
  * device dispatch order is the write order on one JAX stream: hit
    forks are dispatched BEFORE miss inserts in an admission round, and
    stale speculative writes from retired lanes land before the block's
    next owner overwrites them (the same discipline the dense arena
    relies on);
  * bit-exact parity with the dense oracle needs
    ``block_size | max_seq_len`` and per-sequence capacity
    ``T*block_size == max_seq_len`` — both enforced at construction.

Host classes (:class:`BlockAllocator`, :class:`PrefixCache`,
:class:`PagedSlotAllocator`) import no JAX and unit-test at CPU speed;
:class:`PagedKVCacheManager` owns the device pool and the two jitted
programs (scatter-insert, COW-fork)."""

from __future__ import annotations

import dataclasses
import heapq
import re
from collections import OrderedDict, deque
from functools import partial
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np


class BlockAllocator:
    """Refcounted fixed-size block pool with an LRU free list.

    ``alloc`` returns the least-recently-freed block (FIFO recycle order
    keeps just-freed blocks cold longest — their stale speculative
    writes are the furthest back in dispatch order) or None when the
    pool is exhausted; OOM is a value, never an exception."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: Deque[int] = deque(range(num_blocks))
        self.refcount = np.zeros(num_blocks, np.int32)
        self.peak_used = 0

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        block = self._free.popleft()
        self.refcount[block] = 1
        self.peak_used = max(self.peak_used, self.n_used)
        return block

    def incref(self, block: int) -> None:
        if self.refcount[block] < 1:
            raise ValueError(f"block {block} is not allocated")
        self.refcount[block] += 1

    def decref(self, block: int) -> None:
        if self.refcount[block] < 1:
            raise ValueError(f"block {block} is not allocated")
        self.refcount[block] -= 1
        if self.refcount[block] == 0:
            self._free.append(block)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.num_blocks - len(self._free)


@dataclasses.dataclass
class _PrefixEntry:
    blocks: Tuple[int, ...]      # every prompt block, in position order
    prompt_len: int
    first_token: int             # greedy-deterministic token #1


class PrefixCache:
    """LRU map from prompt token bytes -> cached prompt blocks.

    Keyed on the EXACT token sequence (``prompt.tobytes()`` — a
    dict-hashed prompt-token key), so a hit shares the whole prompt:
    full blocks by refcount, the partial tail by COW. Entries hold their
    own refcount on every block, so cached prefixes survive the request
    that created them; eviction (capacity or allocator pressure) drops
    those refs and frees whatever no live request still shares."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self.hits = 0            # successful hit-plan admissions
        self.misses = 0          # successful miss-plan admissions
        self.evictions = 0
        # demotion hook (serving/kv_tiers.py): called with (key, entry)
        # BEFORE the entry's block refs drop, while the blocks still
        # hold their device payload — eviction becomes demotion
        self.on_evict = None

    @staticmethod
    def key_for(prompt) -> bytes:
        return np.asarray(prompt, np.int32).tobytes()

    def lookup(self, key: bytes) -> Optional[_PrefixEntry]:
        """Peek without touching hit/miss counters (the allocator counts
        only on a SUCCESSFUL lease — a deferred or OOM-blocked attempt
        retried every pump must not inflate the rates)."""
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def put(self, key: bytes, blocks: Tuple[int, ...], prompt_len: int,
            first_token: int, block_allocator: BlockAllocator) -> bool:
        if self.capacity <= 0 or key in self._entries:
            return False
        for b in blocks:
            block_allocator.incref(b)
        self._entries[key] = _PrefixEntry(tuple(blocks), prompt_len,
                                          first_token)
        while len(self._entries) > self.capacity:
            self.evict_lru(block_allocator)
        return True

    def pop(self, key: bytes, block_allocator: BlockAllocator) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            for b in entry.blocks:
                block_allocator.decref(b)

    def evict_lru(self, block_allocator: BlockAllocator) -> bool:
        if not self._entries:
            return False
        key, entry = self._entries.popitem(last=False)
        self._drop(key, entry, block_allocator)
        return True

    def demote(self, key: bytes, block_allocator: BlockAllocator) -> bool:
        """Evict ONE entry by key through the demotion hook — the
        explicit 'push this prefix down a tier' verb (tests, and the
        fleet's make-fetchable path)."""
        entry = self._entries.pop(key, None)
        if entry is None:
            return False
        self._drop(key, entry, block_allocator)
        return True

    def _drop(self, key: bytes, entry: _PrefixEntry,
              block_allocator: BlockAllocator) -> None:
        if self.on_evict is not None:
            self.on_evict(key, entry)
        for b in entry.blocks:
            block_allocator.decref(b)
        self.evictions += 1

    def __contains__(self, key: bytes) -> bool:
        """Pure membership peek — no LRU reordering, no counter touch.
        The fleet router probes every replica's cache per placement
        decision; a probe must not refresh entries the replica itself
        never re-used."""
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def blocks_held(self) -> int:
        return sum(len(e.blocks) for e in self._entries.values())


@dataclasses.dataclass
class PagedAdmitPlan:
    """What ``alloc_request`` decided for one admitted request; the
    engine pops it (``take_plan``) and turns it into device work: a
    ``_fork`` dispatch for hits, prefill + scatter-insert (+
    ``commit_prefix``) for misses."""
    slot: int
    hit: bool
    key: Optional[bytes]         # None: prefix caching off for this req
    fill: int                    # prompt_len (the slot's starting fill)
    first_token: Optional[int]   # hits only: cached greedy token #1
    cow: Optional[Tuple[int, int]]   # (src, dst) tail fork; hits only
    n_shared: int                # full blocks shared by refcount


class PagedSlotAllocator:
    """Slot accounting over a block pool: the dense
    :class:`~deepspeed_tpu.serving.kv_cache.SlotAllocator` interface
    (``fill``/``active``/``advance``/``remaining``/``free``/occupancy —
    the scheduler and engine drive both identically) plus block tables,
    request-shaped allocation (``alloc_request``) and prefix-cache
    commit. Host-side only — no JAX."""

    def __init__(self, max_batch: int, max_seq_len: int, *,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefix_cache: Optional[PrefixCache] = None,
                 prefix_caching: bool = True):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_seq_len % block_size != 0:
            raise ValueError(
                f"block_size {block_size} must divide max_seq_len "
                f"{max_seq_len} (bit-parity needs T*block_size == max_seq)")
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.block_size = block_size
        self.blocks_per_seq = max_seq_len // block_size
        if num_blocks is None:
            # pool bytes == dense arena bytes: the equal-HBM comparison
            num_blocks = max_batch * self.blocks_per_seq
        self.blocks = BlockAllocator(num_blocks, block_size)
        self.prefix = prefix_cache if prefix_cache is not None \
            else PrefixCache()
        self.prefix_enabled = prefix_caching
        self._free_slots: List[int] = list(range(max_batch))
        heapq.heapify(self._free_slots)
        self.fill = np.zeros(max_batch, np.int32)
        self.active = np.zeros(max_batch, bool)
        self.tables: List[List[int]] = [[] for _ in range(max_batch)]
        self.plans: Dict[int, PagedAdmitPlan] = {}
        self._pending: set = set()   # prompt keys mid-prefill (defer dups)
        # KVTierManager when tiering is on (PagedKVCacheManager wires
        # it): tier-held prompts defer admission while promoting
        self.tier = None
        self.peak_active = 0
        self.cow_forks = 0

    # ------------------------------------------------------------- leases
    def alloc_request(self, req) -> Optional[int]:
        """Plan one request's admission: lease a slot plus its FULL block
        reservation (prompt + max_new budget), sharing/forking through
        the prefix cache when the prompt is cached. None = not admissible
        yet (no slot, not enough blocks even after cache eviction, or an
        identical prompt is mid-prefill — admitting it next pump turns a
        duplicate prefill into a hit). The decision is recorded in
        ``self.plans[slot]`` for the engine."""
        if not self._free_slots:
            return None
        bs = self.block_size
        pl_ = int(req.prompt_len)
        n_total = -(-(pl_ + int(req.max_new_tokens)) // bs)
        if n_total > self.blocks_per_seq:
            n_total = self.blocks_per_seq    # submit() caps at max_seq_len
        key = PrefixCache.key_for(req.prompt) if self.prefix_enabled \
            else None
        entry = None
        if key is not None:
            if key in self._pending:
                return None
            entry = self.prefix.lookup(key)
            if (entry is None and self.tier is not None
                    and self.tier.holds(key)):
                # tier hit: DEFER (the async promotion is overlapped
                # against running chunks; the engine installs it at a
                # later admission pass and this retry becomes a plain
                # HBM hit) — same retry-next-pump contract as the
                # duplicate-prompt deferral above
                self.tier.request_promotion(key)
                return None
        if entry is not None:
            return self._lease_hit(req, key, entry, n_total)
        return self._lease_miss(req, key, pl_, n_total)

    def _lease_hit(self, req, key, entry, n_total) -> Optional[int]:
        bs = self.block_size
        pl_ = int(req.prompt_len)
        n_full = pl_ // bs                   # shareable read-only
        has_tail = pl_ % bs != 0
        n_new = n_total - n_full             # COW dst (if tail) + fresh
        if not self._ensure_free(n_new):
            return None
        shared = list(entry.blocks[:n_full])
        for b in shared:
            self.blocks.incref(b)
        new_blocks = [self.blocks.alloc() for _ in range(n_new)]
        cow = None
        if has_tail:
            src = entry.blocks[n_full]
            # temporary hold: a later same-round eviction must not free
            # the COW source before the device copy is dispatched
            # (released by PagedKVCacheManager.apply_fork)
            self.blocks.incref(src)
            cow = (src, new_blocks[0])
            self.cow_forks += 1
        slot = self._take_slot(pl_, shared + new_blocks)
        self.plans[slot] = PagedAdmitPlan(
            slot=slot, hit=True, key=key, fill=pl_,
            first_token=entry.first_token, cow=cow, n_shared=n_full)
        self.prefix.hits += 1
        return slot

    def _lease_miss(self, req, key, pl_, n_total) -> Optional[int]:
        if not self._ensure_free(n_total):
            return None
        table = [self.blocks.alloc() for _ in range(n_total)]
        slot = self._take_slot(pl_, table)
        if key is not None:
            self._pending.add(key)
            self.prefix.misses += 1
        self.plans[slot] = PagedAdmitPlan(
            slot=slot, hit=False, key=key, fill=pl_,
            first_token=None, cow=None, n_shared=0)
        return slot

    def _take_slot(self, fill_len: int, table: List[int]) -> int:
        slot = heapq.heappop(self._free_slots)
        self.active[slot] = True
        self.fill[slot] = fill_len
        self.tables[slot] = table
        self.peak_active = max(self.peak_active, self.n_active)
        return slot

    def _ensure_free(self, n: int) -> bool:
        """Evict cold prefix-cache entries until ``n`` blocks are free.
        Entries shared with live requests may free nothing — each
        eviction still retires one entry, so the loop terminates."""
        while self.blocks.n_free < n:
            if not self.prefix.evict_lru(self.blocks):
                return False
        return True

    def alloc_span(self, fill_len: int,
                   n_blocks: int) -> Optional[int]:
        """Lease a slot with EXACTLY ``n_blocks`` fresh blocks at fill
        ``fill_len`` — the migration-import lease: the incoming request
        already has its KV (the bundle carries the block payload), so no
        prefix planning, no admit plan, just a slot whose table can
        receive the scattered blocks. None = no slot or not enough
        blocks even after cache eviction (OOM is a value)."""
        if n_blocks < 1 or n_blocks > self.blocks_per_seq:
            raise ValueError(
                f"n_blocks {n_blocks} out of range [1, "
                f"{self.blocks_per_seq}]")
        if not self._free_slots:
            return None
        if not self._ensure_free(n_blocks):
            return None
        table = [self.blocks.alloc() for _ in range(n_blocks)]
        return self._take_slot(fill_len, table)

    def alloc(self, fill_len: int = 0) -> Optional[int]:
        """Dense-compatible lease (no Request in hand): reserves the full
        per-sequence block budget, skipping the prefix cache. The
        scheduler prefers ``alloc_request``; this exists for drivers and
        tests written against the SlotAllocator interface."""
        if fill_len > self.max_seq_len:
            raise ValueError(
                f"fill_len {fill_len} exceeds max_seq_len {self.max_seq_len}")
        if not self._free_slots:
            return None
        if not self._ensure_free(self.blocks_per_seq):
            return None
        table = [self.blocks.alloc() for _ in range(self.blocks_per_seq)]
        return self._take_slot(fill_len, table)

    def free(self, slot: int) -> None:
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        for b in self.tables[slot]:
            self.blocks.decref(b)
        self.tables[slot] = []
        self.active[slot] = False
        self.fill[slot] = 0
        self.plans.pop(slot, None)
        heapq.heappush(self._free_slots, slot)

    def advance(self, slots) -> None:
        self.fill[np.asarray(slots, np.int64)] += 1

    # ------------------------------------------------------ prefix commit
    def commit_prefix(self, slot: int, key: Optional[bytes],
                      first_token: int) -> Optional[Tuple[int, int]]:
        """After a MISS's prefill lands: cache the prompt blocks under
        ``key``. If the prompt ends mid-block the request's tail block is
        now shared with the cache, so the request COWs it — a fresh block
        replaces it in the table (cache keeps the original). Returns the
        (src, dst) pair the caller must copy on device, or None."""
        if key is None:
            return None
        self._pending.discard(key)
        if not self.active[slot]:
            return None                      # request already retired
        bs = self.block_size
        pl_ = int(self.fill[slot])
        n_prompt = -(-pl_ // bs)
        prompt_blocks = tuple(self.tables[slot][:n_prompt])
        if not self.prefix.put(key, prompt_blocks, pl_, int(first_token),
                               self.blocks):
            return None
        if pl_ % bs == 0:
            return None                      # tail is block-aligned
        src = self.tables[slot][n_prompt - 1]
        dst = self.blocks.alloc()
        if dst is None:
            # cannot privatize the tail: un-cache instead of sharing a
            # block the request is about to write into
            self.prefix.pop(key, self.blocks)
            return None
        self.tables[slot][n_prompt - 1] = dst
        self.blocks.decref(src)              # slot's ref; cache keeps one
        self.cow_forks += 1
        return (src, dst)

    def release_cow_hold(self, block: int) -> None:
        """Drop the temporary refcount a hit plan held on its COW source
        (call strictly AFTER the device copy is dispatched)."""
        self.blocks.decref(block)

    def padded_table(self, slot: int) -> np.ndarray:
        # pad with the num_blocks SENTINEL, not 0: entries past the
        # slot's reservation must never name a real block — a
        # speculative-verify write past the reservation routes through
        # the padding and must hit the kernel's drop guard, while a 0
        # pad would silently corrupt block 0 (likely leased elsewhere)
        out = np.full(self.blocks_per_seq, self.blocks.num_blocks,
                      np.int32)
        table = self.tables[slot]
        out[:len(table)] = table
        return out

    # ------------------------------------------------------------ queries
    def remaining(self, slot: int) -> int:
        """Cache positions still writable: bounded by the slot's OWN
        block reservation, not the arena row extent."""
        return len(self.tables[slot]) * self.block_size \
            - int(self.fill[slot])

    @property
    def pool_capacity_tokens(self) -> int:
        return self.blocks.num_blocks * self.block_size

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    @property
    def n_free(self) -> int:
        return len(self._free_slots)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.max_batch


_WORD = re.compile(r"[^A-Za-z0-9_]+")


def _norm_key(keystr: str) -> str:
    """Normalize a tree_util keystr across container types (dict vs
    FrozenDict render paths differently) for leaf pairing."""
    return _WORD.sub("/", keystr).strip("/")


class PagedKVCacheManager:
    """The device block pool: the model's flax ``cache`` pytree rebuilt
    with every ``cached_key``/``cached_value`` leaf as a flat block pool
    ``[..., num_blocks, block_size, h*d]``, per-slot ``cache_index``
    vectors (as in the dense arena) plus injected ``block_tables``
    leaves ``[..., max_batch, T]`` the decode program reads/writes
    through. Drop-in for
    :class:`~deepspeed_tpu.serving.kv_cache.SlotKVCacheManager` on the
    engine side: same ``insert_batch``/``update``/``arena_report``
    surface, plus ``apply_fork``/``commit_prefix``/``take_plan`` for the
    paged admission flow."""

    def __init__(self, model, params, max_batch: int, *,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefix_cache_capacity: int = 64,
                 prefix_caching: bool = True,
                 slot_axis: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        cfg = getattr(model, "cfg", None)
        self.max_seq_len = int(getattr(cfg, "max_seq_len"))
        # fp itemsize the pool WOULD use without int8 KV (arena_report's
        # kv_bytes_saved baseline)
        self._fp_itemsize = int(jnp.dtype(
            getattr(cfg, "dtype", jnp.float32)).itemsize)
        self.block_size = int(block_size)
        T = self.max_seq_len // self.block_size
        self.allocator = PagedSlotAllocator(
            max_batch, self.max_seq_len, block_size=self.block_size,
            num_blocks=num_blocks,
            prefix_cache=PrefixCache(prefix_cache_capacity),
            prefix_caching=prefix_caching)
        self.num_blocks = self.allocator.blocks.num_blocks
        self.tier = None                     # KVTierManager (attach_tier)
        if slot_axis is None:
            slot_axis = 1 if getattr(cfg, "scan_layers", False) else 0
        self._slot_axis = slot_axis

        # Pool construction from the same eval_shape the dense arena
        # uses: no compute, no compile. kv leaves [.., B, S, h, d] (or
        # already-flat [.., B, S, h*d]) become [.., nb, bs, h*d]; the
        # per-slot cache_index widening matches the dense arena; every
        # attention scope gains a sibling block_tables leaf (stacked
        # [L, B, T] under scan_layers so nn.scan slices it per layer).
        ids = jnp.zeros((max_batch, 1), jnp.int32)
        pos = jnp.zeros((max_batch, 1), jnp.int32)
        shapes = jax.eval_shape(
            partial(model.apply, mutable=["cache"]),
            {"params": params}, ids, positions=pos)
        cache_shapes = shapes[1]["cache"]

        nb, bs, ax = self.num_blocks, self.block_size, self._slot_axis

        def build(node):
            out: Dict[str, Any] = {}
            for name, v in node.items():
                if hasattr(v, "items"):
                    out[name] = build(v)
                elif "cache_index" in name:
                    out[name] = jnp.zeros(v.shape + (max_batch,), jnp.int32)
                else:
                    tail = v.shape[ax + 2:]
                    hd = int(np.prod(tail)) if tail else 1
                    out[name] = jnp.zeros(
                        v.shape[:ax] + (nb, bs, hd), v.dtype)
            if "cached_key" in node:
                idx_shape = node["cache_index"].shape
                out["block_tables"] = jnp.zeros(
                    idx_shape + (max_batch, T), jnp.int32)
            return out

        self.cache = build(cache_shapes)

        keystr = jax.tree_util.keystr
        flatten = jax.tree_util.tree_flatten_with_path

        @partial(jax.jit, donate_argnums=(0,))
        def _insert_paged(pool, pre, tables, slots, fills):
            """Scatter a batch-n prefill cache (leaves [.., n, S, ..],
            S == max_seq_len) into each request's reserved blocks.
            Position p of row i lands at flat pool index
            ``tables[i, p//bs]*bs + p%bs``; positions past the true
            prompt length route to the out-of-range sentinel and drop —
            a fresh block's tail stays whatever it held until the
            request's own decode writes it (masked until then, exactly
            like the dense arena's stale rows)."""
            pre_by_norm = {_norm_key(keystr(p)): leaf
                           for p, leaf in flatten(pre)[0]}

            def leaf(path, a):
                ks = keystr(path)
                if "block_tables" in ks:
                    return a.at[..., slots, :].set(tables)
                if "cache_index" in ks:
                    return a.at[..., slots].set(fills)
                o = pre_by_norm[_norm_key(ks)]
                lead = a.ndim - 3
                hd = a.shape[-1]
                n = o.shape[lead]
                S = o.shape[lead + 1]
                of = o.astype(a.dtype).reshape(
                    o.shape[:lead] + (n, S, hd))
                p = jnp.arange(S)
                blk = jnp.take(tables, p // bs, axis=1)          # [n, S]
                flat = blk * bs + (p % bs)[None, :]
                flat = jnp.where(p[None, :] < fills[:, None], flat,
                                 nb * bs)                        # sentinel
                flat = flat.reshape(n * S)

                def scat(pf, off):
                    return pf.reshape(nb * bs, hd).at[flat].set(
                        off.reshape(n * S, hd),
                        mode="drop").reshape(nb, bs, hd)

                f = scat
                for _ in range(lead):
                    f = jax.vmap(f)
                return f(a, of)

            return jax.tree_util.tree_map_with_path(leaf, pool)

        self._insert_paged = _insert_paged

        @partial(jax.jit, donate_argnums=(0,))
        def _fork(pool, slot, table_row, fill, src, dst):
            """Install one slot's lane state (block-table row + fill) and
            copy block src -> dst in every kv pool leaf — the COW fork.
            src == dst is the no-COW case (self-copy, a no-op write);
            one compiled program serves every hit admission."""
            def leaf(path, a):
                ks = keystr(path)
                if "block_tables" in ks:
                    return a.at[..., slot, :].set(table_row)
                if "cache_index" in ks:
                    return a.at[..., slot].set(fill)
                lead = a.ndim - 3
                blk = jnp.take(a, src, axis=lead)
                idx = (slice(None),) * lead + (dst,)
                return a.at[idx].set(blk)
            return jax.tree_util.tree_map_with_path(leaf, pool)

        self._fork = _fork

    # ----------------------------------------------------------- mutation
    def insert_batch(self, prefill_cache: Any, slots, fills) -> None:
        """Move a batch-n prefill cache into the n slots' reserved
        blocks. Donates and replaces the pool; compiles one program per
        batch size n (the prefill cache's S extent is always the model's
        full max_seq_len, so only n varies)."""
        import jax.numpy as jnp
        tables = np.stack([self.allocator.padded_table(int(s))
                           for s in slots])
        self.cache = self._insert_paged(
            self.cache, prefill_cache, jnp.asarray(tables),
            jnp.asarray(np.asarray(slots, np.int32)),
            jnp.asarray(np.asarray(fills, np.int32)))

    def apply_fork(self, plan: PagedAdmitPlan) -> None:
        """Realize a prefix-cache HIT on device: install the slot's
        block table + fill and COW-copy the partial tail block (self-copy
        when the prompt is block-aligned). Releases the plan's temporary
        hold on the COW source once the copy is in the dispatch queue."""
        import jax.numpy as jnp
        if plan.cow is not None:
            src, dst = plan.cow
        else:
            src = dst = self.allocator.tables[plan.slot][0]
        self.cache = self._fork(
            self.cache, jnp.int32(plan.slot),
            jnp.asarray(self.allocator.padded_table(plan.slot)),
            jnp.int32(plan.fill), jnp.int32(src), jnp.int32(dst))
        if plan.cow is not None:
            self.allocator.release_cow_hold(plan.cow[0])

    def commit_prefix(self, plan: PagedAdmitPlan,
                      first_token: int) -> Optional[Tuple[int, int]]:
        """After a MISS's prefill + insert: publish the prompt blocks to
        the prefix cache and, when the prompt ends mid-block, dispatch
        the request-side COW copy so the cached tail stays immutable."""
        import jax.numpy as jnp
        cow = self.allocator.commit_prefix(plan.slot, plan.key,
                                           first_token)
        if cow is not None:
            src, dst = cow
            self.cache = self._fork(
                self.cache, jnp.int32(plan.slot),
                jnp.asarray(self.allocator.padded_table(plan.slot)),
                jnp.int32(int(self.allocator.fill[plan.slot])),
                jnp.int32(src), jnp.int32(dst))
        return cow

    def take_plan(self, slot: int) -> PagedAdmitPlan:
        return self.allocator.plans.pop(slot)

    def install_table(self, slot: int) -> None:
        """Install a MISS lane's block table + fill on device WITHOUT a
        prefill insert — the fused-prefill admission path: the decode
        scan itself writes the prompt's KV block-granularly, it only
        needs the lane's table row and write index live first. Reuses
        the hit-fork program with a self-copy (src == dst, a no-op
        block write)."""
        import jax.numpy as jnp
        t0 = int(self.allocator.tables[slot][0])
        self.cache = self._fork(
            self.cache, jnp.int32(slot),
            jnp.asarray(self.allocator.padded_table(slot)),
            jnp.int32(int(self.allocator.fill[slot])),
            jnp.int32(t0), jnp.int32(t0))

    def abandon_plan(self, plan: PagedAdmitPlan) -> None:
        """Walk back a MISS plan whose lane retired before its first
        token (fused-prefill cancel / expiry mid-prompt): drop the
        pending-prompt key so duplicate prompts stop deferring on a
        commit that will never come. The lane's blocks free through the
        normal slot release."""
        if plan.key is not None:
            self.allocator._pending.discard(plan.key)

    # ------------------------------------------------- block portability
    def export_blocks(self, slot: int,
                      n_blocks: Optional[int] = None) -> Dict[str, Any]:
        """Gather one slot's leased KV blocks off-device: the payload a
        live migration ships. Returns ``{normalized leaf key ->
        np.ndarray [..., n, block_size, h*d]}`` in block-TABLE order
        (position order), for every kv pool leaf — index leaves
        (cache_index / block_tables) are reconstructed at import, never
        shipped. One eager gather per leaf; migration is a rare
        host-paced op, so nothing here is jitted (no retrace-budget
        surface)."""
        table = self.allocator.tables[slot]
        if n_blocks is None:
            n_blocks = len(table)
        return self.export_block_ids(table[:n_blocks])

    def export_block_ids(self, blocks) -> Dict[str, Any]:
        """``export_blocks`` by explicit block-id list (position order)
        instead of a slot's table — the tier-demotion gather reads a
        prefix-cache entry's blocks, which belong to no slot. Same
        eager no-jit rationale: demotion is host-paced, and the gather
        is dispatched before the caller's decrefs can recycle the
        blocks, so the payload is the pre-overwrite bytes."""
        import jax
        import jax.numpy as jnp
        idx = jnp.asarray(np.asarray(list(blocks), np.int32))
        gathered: Dict[str, Any] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            ks = jax.tree_util.keystr(path)
            if "cache_index" in ks or "block_tables" in ks:
                continue
            lead = leaf.ndim - 3
            gathered[_norm_key(ks)] = jnp.take(leaf, idx, axis=lead)
        # one transfer for the whole tree — per-leaf np.asarray would
        # block on a device sync per leaf, which shows up directly in
        # the demotion path's host time
        return jax.device_get(gathered)

    def import_blocks(self, slot: int, leaves: Dict[str, Any]) -> None:
        """Scatter exported block payloads into ``slot``'s freshly
        leased blocks (``alloc_span``) and install the slot's table row +
        write cursor on device — the receiving half of a live migration.
        ``leaves`` maps normalized leaf keys (``export_blocks`` output)
        to ``[..., n, block_size, h*d]`` arrays; ``n`` may be smaller
        than the lease (only written blocks ship). Eager per-leaf
        scatter, same rare-op rationale as ``export_blocks``."""
        import jax
        import jax.numpy as jnp
        table = self.allocator.tables[slot]
        fill = int(self.allocator.fill[slot])

        def leaf(path, a):
            ks = jax.tree_util.keystr(path)
            if "block_tables" in ks:
                return a.at[..., slot, :].set(
                    jnp.asarray(self.allocator.padded_table(slot)))
            if "cache_index" in ks:
                return a.at[..., slot].set(jnp.int32(fill))
            payload = leaves.get(_norm_key(ks))
            if payload is None:
                raise KeyError(
                    f"migration bundle is missing kv leaf {ks!r}")
            lead = a.ndim - 3
            n = payload.shape[lead]
            idx = jnp.asarray(np.asarray(table[:n], np.int32))
            sel = (slice(None),) * lead + (idx,)
            return a.at[sel].set(jnp.asarray(payload).astype(a.dtype))

        self.cache = jax.tree_util.tree_map_with_path(leaf, self.cache)

    def update(self, new_cache: Any) -> None:
        self.cache = new_cache

    # ------------------------------------------------------------ tiering
    def attach_tier(self, tier) -> None:
        """Wire a :class:`~deepspeed_tpu.serving.kv_tiers.KVTierManager`
        behind the allocator: prefix-cache eviction becomes DEMOTION
        (gather + DRAM admit), and tier-held prompts defer admission
        while their async promotion runs."""
        self.tier = tier
        self.allocator.tier = tier
        self.allocator.prefix.on_evict = self._demote_entry

    def _demote_entry(self, key: bytes, entry) -> None:
        """Eviction hook (engine thread — eviction fires inside
        allocator calls the engine drives): gather the entry's blocks
        off-device and admit them to the DRAM tier."""
        if self.tier is None or key is None:
            return
        leaves = self.export_block_ids(entry.blocks)
        self.tier.admit(key, entry.prompt_len, entry.first_token, leaves)

    def demote_prefix(self, key: bytes) -> bool:
        """Explicitly push one cached prefix down to the tier (tests and
        the fleet's make-fetchable path). Engine thread only."""
        return self.allocator.prefix.demote(key, self.allocator.blocks)

    def readmit_prefix(self, key: bytes, prompt_len: int,
                       first_token: int, leaves: Dict[str, Any]) -> bool:
        """Install a completed promotion back into HBM: lease blocks,
        eagerly scatter the payload into them (the import_blocks pattern
        — no slot, no table row), and republish the prefix-cache entry.
        The next ``alloc_request`` for this prompt is then a plain HBM
        hit. Returns False when the pool cannot free enough blocks —
        the caller returns the payload to the tier and retries later.
        Engine thread only; eager, zero jit variants."""
        installed, _rejected = self.readmit_prefix_many(
            [(key, prompt_len, first_token, leaves)])
        return bool(installed)

    def readmit_prefix_many(self, entries):
        """Batched :meth:`readmit_prefix`: every promotion that drained
        ready in the same admission pass installs through ONE scatter
        per pool leaf (indices and payloads concatenated on the block
        axis). Eager-op dispatch dominates the install cost, so k
        simultaneous promotions cost one entry's dispatch, not k.
        ``entries`` is ``[(key, prompt_len, first_token, leaves), ...]``;
        returns ``(installed_keys, rejected_entries)`` where rejected
        entries did not fit the pool (caller returns them to the tier).
        Engine thread only; eager, zero jit variants."""
        import jax
        import jax.numpy as jnp
        al = self.allocator
        bs = self.block_size
        installed: list = []
        rejected: list = []
        plan: list = []           # (key, plen, ftok, leaves, blocks)
        for key, plen, ftok, leaves in entries:
            if al.prefix.lookup(key) is not None:
                installed.append(key)        # re-prefilled meanwhile
                continue
            n = -(-int(plen) // bs)
            if not al._ensure_free(n):
                rejected.append((key, plen, ftok, leaves))
                continue
            plan.append((key, plen, ftok, leaves,
                         [al.blocks.alloc() for _ in range(n)]))
        if not plan:
            return installed, rejected
        idx = jnp.asarray(np.asarray(
            [b for *_, blks in plan for b in blks], np.int32))

        def leaf(path, a):
            ks = jax.tree_util.keystr(path)
            if "cache_index" in ks or "block_tables" in ks:
                return a
            lead = a.ndim - 3
            parts = []
            for _key, _plen, _ftok, leaves, _blks in plan:
                payload = leaves.get(_norm_key(ks))
                if payload is None:
                    raise KeyError(
                        f"promotion payload is missing kv leaf {ks!r}")
                parts.append(np.asarray(payload))
            payload = parts[0] if len(parts) == 1 else \
                np.concatenate(parts, axis=lead)
            sel = (slice(None),) * lead + (idx,)
            return a.at[sel].set(jnp.asarray(payload).astype(a.dtype))

        self.cache = jax.tree_util.tree_map_with_path(leaf, self.cache)
        for key, plen, ftok, _leaves, blks in plan:
            al.prefix.put(key, tuple(blks), int(plen), int(ftok),
                          al.blocks)
            for b in blks:
                al.blocks.decref(b)          # cache holds the sole ref
            installed.append(key)
        return installed, rejected

    # ---------------------------------------------------------- accounting
    def arena_report(self) -> dict:
        """Block-pool HBM accounting: the paged analogue of the dense
        ``arena_report``. Keeps the dense report's load-bearing keys
        (``arena_bytes``/``kv_bytes``/``index_bytes``/``bytes_per_slot``/
        ``headroom_bytes``/``n_active``/``n_free``) so the engine gauges
        and bench specs read both layouts, and adds the block-pool view:
        bytes per block, blocks total/used/free/peak, and the prefix
        cache's share of the pool."""
        import jax
        kv_bytes = 0
        index_bytes = 0
        int8_payload = 0
        scale_bytes = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is None:
                continue
            ks = jax.tree_util.keystr(path)
            if "cache_index" in ks or "block_tables" in ks:
                index_bytes += int(nbytes)
            else:
                kv_bytes += int(nbytes)
                if "scale" in ks:
                    scale_bytes += int(nbytes)
                elif leaf.dtype == np.int8:
                    int8_payload += int(nbytes)
        kv_bytes_fp = (kv_bytes - int8_payload - scale_bytes
                       + int8_payload * self._fp_itemsize)
        al = self.allocator
        bytes_per_block = kv_bytes // self.num_blocks
        bytes_per_token = bytes_per_block // self.block_size \
            if self.block_size else 0
        per_slot = bytes_per_token * self.max_seq_len
        used = al.blocks.n_used
        free_ = al.blocks.n_free
        held = al.prefix.blocks_held
        rep = {
            "layout": "paged",
            "arena_bytes": kv_bytes + index_bytes,
            "kv_bytes": kv_bytes,
            "index_bytes": index_bytes,
            "int8_payload_bytes": int8_payload,
            "scale_bytes": scale_bytes,
            "kv_bytes_fp_equiv": kv_bytes_fp,
            "kv_bytes_saved": kv_bytes_fp - kv_bytes,
            "max_batch": al.max_batch,
            "max_seq_len": self.max_seq_len,
            "block_size": self.block_size,
            "blocks_total": self.num_blocks,
            "blocks_used": used,
            "blocks_free": free_,
            "blocks_peak_used": al.blocks.peak_used,
            "blocks_per_seq": al.blocks_per_seq,
            "bytes_per_block": bytes_per_block,
            "bytes_per_token": bytes_per_token,
            "bytes_per_slot": per_slot,
            "n_active": al.n_active,
            "n_free": al.n_free,
            "active_bytes": used * bytes_per_block,
            "headroom_bytes": free_ * bytes_per_block,
            "prefix_cache_entries": len(al.prefix),
            "prefix_cache_blocks": held,
            "prefix_cache_share": held / self.num_blocks,
        }
        if self.tier is not None:
            # per-tier accounting rides along under its own versioned
            # schema (dstpu-tiers-v1): hbm_* mirrors the pool numbers so
            # the tiers block reads standalone on dashboards
            tiers = self.tier.report()
            tiers["hbm_bytes"] = rep["active_bytes"]
            tiers["hbm_capacity_bytes"] = kv_bytes
            tiers["hbm_blocks"] = used
            rep["tiers"] = tiers
        return rep

    # ---------------------------------------------- allocator passthrough
    @property
    def prefix_enabled(self) -> bool:
        return self.allocator.prefix_enabled

    @property
    def prefix_cache(self) -> PrefixCache:
        return self.allocator.prefix

    def alloc(self, fill_len: int = 0) -> Optional[int]:
        return self.allocator.alloc(fill_len)

    def free(self, slot: int) -> None:
        self.allocator.free(slot)

    @property
    def fill(self) -> np.ndarray:
        return self.allocator.fill

    @property
    def occupancy(self) -> float:
        return self.allocator.occupancy
