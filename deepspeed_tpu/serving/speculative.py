"""Self-drafting speculative decoding for the chunked serving loop.

Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding": a cheap drafter proposes k tokens, the target model scores all
k+1 positions in ONE batched forward, and an accept-prefix +
rejection-resampling rule emits between 1 and k+1 tokens whose joint
distribution is EXACTLY the target model's. Everything here is traceable
jax — the engine runs it inside the ``lax.scan`` chunk body
(serving/engine.py decode_chunk_spec_fn), so the host loop and
double-buffered launch protocol are untouched; a chunk of K scan steps
simply emits a variable number of tokens per lane per step.

The built-in drafter is PROMPT-LOOKUP (n-gram): find the most recent
earlier occurrence of the trailing n-gram of the lane's history and
propose its continuation. No second model, no extra params, no extra
forward — drafting is a few gathers over the [B, S] history buffer the
engine threads through the chunk carry. The ``Drafter`` protocol keeps
the slot open for a real draft model later: anything with a ``k``
attribute and a traceable ``propose(hist, tok, pos) -> [B, k]`` works.

Exactness:
  * greedy (temperature == 0): the drafter proposes deltas; verification
    accepts the longest prefix where draft == argmax(target). Emitted
    tokens are argmax(target) at every position up to and including the
    first mismatch — exactly the sequence the one-token-at-a-time greedy
    loop produces, because the model's s>1 cached forward is positionwise
    bit-identical to s=1 (the repo's masked_cache_attention is shared by
    both shapes). Bit-identical to ``generate()``, gated by the parity
    asserts in serving_bench and tests.
  * sampled (temperature > 0): a delta-distribution drafter (q = 1 on the
    proposed token) accepts draft d_j with probability p_j(d_j); on the
    first rejection it resamples from the residual ``p_j`` with index
    ``d_j`` zeroed and renormalized — the standard rejection-resampling
    identity then gives emitted ~ p_j exactly. When all k drafts are
    accepted, a bonus token samples from p_k for free.
"""

from __future__ import annotations

from typing import Protocol, Tuple

import jax
import jax.numpy as jnp


class Drafter(Protocol):
    """Pluggable draft-proposal strategy. ``propose`` must be traceable
    (it runs inside the jitted chunk scan) and is called with the
    device-resident history ``hist`` [B, S] (row b's tokens 0..pos[b],
    prompt + emitted, with ``hist[b, pos[b]] == tok[b]``), the current
    last token ``tok`` [B] and its position ``pos`` [B]; it returns k
    proposed continuation tokens [B, k] int32."""

    k: int

    def propose(self, hist: jnp.ndarray, tok: jnp.ndarray,
                pos: jnp.ndarray) -> jnp.ndarray: ...


class NGramDrafter:
    """Prompt-lookup decoding (n-gram self-drafting): match the trailing
    ``n``-gram of each lane's history against every earlier position and
    continue from just after the MOST RECENT match, wrapping with the
    match period so all k proposals come from real history. Lanes with no
    match propose ``tok`` repeated (last-token repetition — the cheapest
    guess, and the right one for degenerate repetition loops)."""

    def __init__(self, k: int = 4, n: int = 2):
        if k < 1:
            raise ValueError(f"draft length k must be >= 1, got {k}")
        if n < 1:
            raise ValueError(f"n-gram order must be >= 1, got {n}")
        self.k = int(k)
        self.n = int(n)

    def propose(self, hist: jnp.ndarray, tok: jnp.ndarray,
                pos: jnp.ndarray) -> jnp.ndarray:
        B, S = hist.shape
        k, n = self.k, self.n
        hlen = pos + 1                                   # tokens in history
        idx = jnp.arange(S, dtype=jnp.int32)[None, :]    # candidate ends
        match = jnp.ones((B, S), bool)
        for t in range(n):
            # hist[b, idx - t] == hist[b, hlen-1-t]: roll brings position
            # idx-t to column idx (wrap-around columns are excluded by the
            # idx >= n-1 validity mask below)
            ref_t = jnp.take_along_axis(
                hist, jnp.clip(hlen - 1 - t, 0, S - 1)[:, None], axis=1)
            match = match & (jnp.roll(hist, t, axis=1) == ref_t)
        valid = match & (idx >= n - 1) & (idx < hlen[:, None] - 1)
        jstar = jnp.max(jnp.where(valid, idx, -1), axis=1)   # [B]
        found = jstar >= 0
        # continue after the match, wrapping with the period so proposals
        # past the matched span re-walk the repeating cycle
        period = jnp.maximum(hlen - 1 - jstar, 1)
        i = jnp.arange(k, dtype=jnp.int32)[None, :]
        src = jnp.clip(jstar[:, None] + 1 + i % period[:, None], 0, S - 1)
        drafts = jnp.take_along_axis(hist, src, axis=1)
        return jnp.where(found[:, None], drafts, tok[:, None])


def verify_greedy(logits: jnp.ndarray, drafts: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy verification. ``logits`` [B, k+1, V]: target scores at the
    k+1 positions fed (last token + k drafts); ``drafts`` [B, k].
    Returns ``(emitted [B, k+1], acc [B])``: ``acc`` counts accepted
    drafts (0..k) and positions 0..acc of ``emitted`` are the real
    output (acc+1 tokens) — exactly what sequential greedy would emit,
    since emitted_j == argmax_j and drafts agree on the accepted
    prefix."""
    tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # [B, k+1]
    k = drafts.shape[1]
    ok = (drafts == tgt[:, :k]).astype(jnp.int32)
    acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)           # [B]
    return tgt, acc


def verify_rejection(logits: jnp.ndarray, drafts: jnp.ndarray, key,
                     temperature: float, top_k, top_p, filter_fn=None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rejection-resampling verification at temperature > 0 against the
    SAME filtered distribution ``sample_tokens`` draws from (temperature /
    top-k / top-p applied before the softmax — serving/sampling.py
    filter_logits). Draft j is accepted with probability p_j(d_j) (the
    delta-drafter accept rule); the first rejected position resamples
    from the residual (p_j with the draft index zeroed, renormalized),
    and a fully-accepted chunk samples a bonus token from p_k. Returns
    ``(emitted [B, k+1], acc [B])`` with positions 0..acc real — the
    emitted tokens are distributed exactly as k+1 sequential draws.

    ``filter_fn`` overrides the logit filter (the megakernel engine
    passes serving/sampling.fused_filter_logits so the filter runs in the
    sort-free Pallas epilogue); it must implement filter_logits'
    masked-logit contract."""
    if filter_fn is None:
        from .sampling import filter_logits as filter_fn
    B, kp1, _ = logits.shape
    k = kp1 - 1
    probs = jax.nn.softmax(
        filter_fn(logits, temperature, top_k, top_p), axis=-1)
    ukey, rkey, bkey = jax.random.split(key, 3)
    p_draft = jnp.take_along_axis(
        probs[:, :k], drafts[..., None], axis=-1)[..., 0]    # [B, k]
    accept = jax.random.uniform(ukey, (B, k)) < p_draft
    acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # residual at every draft position (only position ``acc`` is used):
    # zero the rejected draft's mass and renormalize
    res = probs[:, :k] * (1.0 - jax.nn.one_hot(
        drafts, probs.shape[-1], dtype=probs.dtype))
    res_logits = jnp.where(res > 0, jnp.log(jnp.maximum(res, 1e-30)),
                           -1e9)
    rescue = jax.random.categorical(rkey, res_logits, axis=-1)   # [B, k]
    bonus_logits = jnp.where(
        probs[:, k] > 0, jnp.log(jnp.maximum(probs[:, k], 1e-30)), -1e9)
    bonus = jax.random.categorical(bkey, bonus_logits, axis=-1)  # [B]
    correction = jnp.concatenate(
        [rescue.astype(jnp.int32), bonus[:, None].astype(jnp.int32)],
        axis=1)                                                  # [B, k+1]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
    j = jnp.arange(kp1, dtype=jnp.int32)[None, :]
    emitted = jnp.where(j < acc[:, None], drafts_pad, correction)
    return emitted, acc
