"""Named-axis cartesian process topology.

TPU-native re-design of the reference's ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology`` at topology.py:12, ``PipeDataParallelTopology``:235,
``PipeModelDataParallelTopology``:246, ``PipelineParallelGrid``:252). The
semantics are the same — a cartesian grid of ranks addressed by named axis
coordinates — but here the topology doubles as the factory for a
``jax.sharding.Mesh``, so the same object answers both "which global rank has
coord (pipe=1, data=3)" and "give me the device mesh whose axes carry the
collectives".

Rank order is row-major over the axis order given at construction (the last
axis varies fastest), matching the reference's convention that adjacent data-
parallel ranks are adjacent global ranks when ``data`` is last.
"""

from __future__ import annotations

import itertools
from collections import namedtuple
from typing import Dict, List, Optional, Sequence


class ProcessTopology:
    """Maps n-dimensional named coordinates <-> flat global ranks."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate axis names in {axes}")
        for d in dims:
            if d < 1:
                raise ValueError(f"all dims must be >= 1, got {dims}")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)

        self._coord_to_rank: Dict[tuple, int] = {}
        self._rank_to_coord: List[tuple] = []
        for rank, coord in enumerate(itertools.product(*[range(d) for d in dims])):
            c = self.ProcessCoord(*coord)
            self._coord_to_rank[c] = rank
            self._rank_to_coord.append(c)

    def world_size(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def get_rank(self, **coord_kwargs) -> int:
        if sorted(coord_kwargs.keys()) != sorted(self.axes):
            raise ValueError(
                f"get_rank() needs all axes {self.axes}, got {list(coord_kwargs)}")
        return self._coord_to_rank[self.ProcessCoord(**coord_kwargs)]

    def get_coord(self, rank: int):
        return self._rank_to_coord[rank]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_", outer_sep="-") -> str:
        omit = set(omit_axes)
        coord = self.get_coord(rank)
        parts = [
            f"{axis}{inner_sep}{getattr(coord, axis):02d}"
            for axis in self.axes
            if axis not in omit
        ]
        return outer_sep.join(parts)

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coordinates match every given axis=value filter."""
        for axis in filter_kwargs:
            if axis not in self.axes:
                raise ValueError(f"unknown axis {axis!r}; have {self.axes}")

        def matches(coord):
            return all(getattr(coord, a) == v for a, v in filter_kwargs.items())

        return [r for r, c in enumerate(self._rank_to_coord) if matches(c)]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        """Ranks whose coordinate along `axis` equals `idx`."""
        return self.filter_match(**{axis: idx})

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along `axis` (the comm groups for
        a collective over that axis)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for combo in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, combo))
            group = [
                self.get_rank(**{**fixed, axis: i})
                for i in range(self.get_dim(axis))
            ]
            lists.append(group)
        return lists

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """2-d (pipe, data) grid; data-parallel ranks are adjacent (innermost)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3-d (pipe, data, model) grid for 3D parallelism; model innermost so
    tensor-parallel partners share a host/ICI neighborhood."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Rank bookkeeping for 3D (pipe x data x model) parallelism.

    Re-provides the reference ``PipelineParallelGrid`` query surface
    (stage/data/model ids, p2p neighbors, per-axis rank groups), but instead
    of building torch process groups it exposes rank lists; collectives are
    carried by mesh axes (see parallel/mesh.py) and stage-to-stage transfer
    rides `ppermute` over the 'pipe' axis.
    """

    def __init__(self, topology: Optional[ProcessTopology] = None,
                 process_group=None, world_size: Optional[int] = None,
                 global_rank: int = 0):
        if topology is None:
            if world_size is None:
                raise ValueError("need a topology or a world_size")
            # Default: pure data parallel.
            topology = PipeDataParallelTopology(num_pp=1, num_dp=world_size)
        self._topo = topology
        self.global_rank = global_rank

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.slice_parallel_size = self.model_parallel_size
        self.world_size = topology.world_size()

        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in topology.get_axis_names() else 0

        # Rank groups per axis (lists of global ranks).
        self.dp_groups = topology.get_axis_comm_lists("data")
        self.pp_groups = topology.get_axis_comm_lists("pipe")
        self.mp_groups = topology.get_axis_comm_lists("model") if "model" in topology.get_axis_names() else []

        # p2p: pairs of adjacent pipeline stages sharing all other coords.
        self.p2p_groups = self._build_p2p_groups()

    def _build_p2p_groups(self) -> List[List[int]]:
        if "pipe" not in self._topo.get_axis_names() or self.pipe_parallel_size < 2:
            return []
        pairs = []
        for group in self._topo.get_axis_comm_lists("pipe"):
            for i in range(len(group)):
                pairs.append(sorted([group[i], group[(i + 1) % len(group)]]))
        return pairs

    # ---- queries mirroring the reference surface -------------------------
    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_id(self) -> int:
        return self.model_parallel_id

    def get_global_rank(self) -> int:
        return self.global_rank

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_data_parallel_group_ranks(self) -> List[int]:
        return self._topo.filter_match(
            **{a: getattr(self._topo.get_coord(self.global_rank), a)
               for a in self._topo.get_axis_names() if a != "data"})

    def get_pipe_parallel_group_ranks(self) -> List[int]:
        return self._topo.filter_match(
            **{a: getattr(self._topo.get_coord(self.global_rank), a)
               for a in self._topo.get_axis_names() if a != "pipe"})

    def get_model_parallel_group_ranks(self) -> List[int]:
        if "model" not in self._topo.get_axis_names():
            return [self.global_rank]
        return self._topo.filter_match(
            **{a: getattr(self._topo.get_coord(self.global_rank), a)
               for a in self._topo.get_axis_names() if a != "model"})

    def stage_to_global(self, stage_id: int) -> int:
        """Global rank of `stage_id` holding my other coordinates."""
        coord = self._topo.get_coord(self.global_rank)
        kwargs = {a: getattr(coord, a) for a in self._topo.get_axis_names()}
        kwargs["pipe"] = stage_id
        return self._topo.get_rank(**kwargs)

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    @property
    def topology(self) -> ProcessTopology:
        return self._topo
