"""Device-mesh construction and sharding-rule helpers.

This is the load-bearing seam of the framework (reference analogue: the
process-group machinery spread across ``deepspeed/comm``, ``utils/groups.py``
and ``runtime/pipe/topology.py``). Instead of NCCL process groups we build one
``jax.sharding.Mesh`` with named axes and express every parallel strategy as a
sharding over those axes:

  - ``dp``  : data parallelism; ZeRO stages shard grads/optimizer/params here.
  - ``tp``  : tensor (model) parallelism; matmul psum rides this axis.
  - ``pp``  : pipeline stages; stage p2p is a ``ppermute`` over this axis.
  - ``ep``  : expert parallelism; MoE all-to-all rides this axis.
  - ``sp``  : sequence/context parallelism (Ulysses-style all-to-all).

Axes of size 1 are kept in the mesh so sharding specs are stable regardless of
configuration. Mesh axis order puts ``dp`` outermost (DCN-friendly) and
``tp`` innermost (ICI-friendly), matching TPU topology: tensor-parallel
partners need the highest bandwidth.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order: outermost (slowest, DCN-tolerant) to innermost
# (fastest, wants ICI).
MESH_AXES = ("dp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshShape:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def total(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    def as_dict(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in MESH_AXES}

    @staticmethod
    def infer(n_devices: int, tp: int = 1, pp: int = 1, ep: int = 1, sp: int = 1,
              dp: Optional[int] = None) -> "MeshShape":
        """Fill in dp so the mesh covers all devices."""
        denom = tp * pp * ep * sp
        if dp is None:
            if n_devices % denom != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by tp*pp*ep*sp={denom}")
            dp = n_devices // denom
        shape = MeshShape(dp=dp, pp=pp, ep=ep, sp=sp, tp=tp)
        if shape.total() != n_devices:
            raise ValueError(
                f"mesh {shape.as_dict()} covers {shape.total()} devices, "
                f"have {n_devices}")
        return shape


def build_mesh(shape: MeshShape, devices: Optional[Sequence] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape.total() != n:
        raise ValueError(f"mesh needs {shape.total()} devices, got {n}")
    dims = [getattr(shape, a) for a in MESH_AXES]
    dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, MESH_AXES)


_GLOBAL_MESH: Optional[Mesh] = None
_GLOBAL_SHAPE: Optional[MeshShape] = None


def set_global_mesh(mesh: Mesh, shape: MeshShape) -> None:
    global _GLOBAL_MESH, _GLOBAL_SHAPE
    _GLOBAL_MESH = mesh
    _GLOBAL_SHAPE = shape


def get_global_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        shape = MeshShape.infer(len(jax.devices()))
        set_global_mesh(build_mesh(shape), shape)
    return _GLOBAL_MESH


def get_global_mesh_shape() -> MeshShape:
    get_global_mesh()
    return _GLOBAL_SHAPE


def reset_global_mesh() -> None:
    global _GLOBAL_MESH, _GLOBAL_SHAPE
    _GLOBAL_MESH = None
    _GLOBAL_SHAPE = None


# Model-internal sharding constraints (MoE dispatch, Ulysses, partitioned
# activations) resolve their mesh here. Default: the process-global mesh.
# The pipeline engine overrides it per stage program so the SAME model code
# constrains against the stage sub-mesh (which carries dp/ep/tp axes of its
# own) — the analogue of the reference's expert groups being built from the
# pipe topology's stage ranks (runtime/pipe/topology.py:246).
_CONSTRAINT_MESH: Optional[Mesh] = None


class use_constraint_mesh:
    """Context manager: constraints inside trace against ``mesh``."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        global _CONSTRAINT_MESH
        self._prev = _CONSTRAINT_MESH
        _CONSTRAINT_MESH = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        global _CONSTRAINT_MESH
        _CONSTRAINT_MESH = self._prev
        return False


def get_constraint_mesh() -> Mesh:
    return _CONSTRAINT_MESH if _CONSTRAINT_MESH is not None \
        else get_global_mesh()


def axis_size(axis: str, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_global_mesh()
    return mesh.shape[axis]


# ---------------------------------------------------------------------------
# Sharding-rule helpers (the ZeRO mapping lives on top of these).
# ---------------------------------------------------------------------------

def shard_leading_divisible(shape: Tuple[int, ...], axes: Sequence[str],
                            mesh: Optional[Mesh] = None) -> P:
    """PartitionSpec sharding the first dim divisible by the product of the
    given mesh axes; replicate if nothing divides. This is the generic rule
    used to shard flat optimizer-state / master-param tensors over ``dp``
    (ZeRO-1/2/3) without per-tensor hand annotation."""
    mesh = mesh or get_global_mesh()
    group = math.prod(mesh.shape[a] for a in axes)
    if group == 1:
        return P()
    for i, d in enumerate(shape):
        if d % group == 0 and d > 0:
            spec = [None] * len(shape)
            spec[i] = tuple(axes) if len(axes) > 1 else axes[0]
            return P(*spec)
    return P()


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_global_mesh(), spec)


def tree_shard_over(tree, axes: Sequence[str], mesh: Optional[Mesh] = None):
    """Sharding pytree: every array leaf sharded by shard_leading_divisible."""
    mesh = mesh or get_global_mesh()

    def leaf_sharding(x):
        shape = getattr(x, "shape", ())
        return named_sharding(shard_leading_divisible(tuple(shape), axes, mesh), mesh)

    return jax.tree_util.tree_map(leaf_sharding, tree)


def tree_replicated(tree, mesh: Optional[Mesh] = None):
    mesh = mesh or get_global_mesh()
    sh = named_sharding(P(), mesh)
    return jax.tree_util.tree_map(lambda _: sh, tree)


def batch_sharding(mesh: Optional[Mesh] = None, extra_axes: Sequence[str] = ()) -> NamedSharding:
    """Batch dim sharded over dp (and optionally ep/sp) axes."""
    axes = ("dp",) + tuple(extra_axes)
    mesh = mesh or get_global_mesh()
    axes = tuple(a for a in axes if mesh.shape[a] > 1) or ("dp",)
    return named_sharding(P(axes if len(axes) > 1 else axes[0]), mesh)
