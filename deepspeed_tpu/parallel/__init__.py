from .mesh import (  # noqa: F401
    MESH_AXES,
    MeshShape,
    axis_size,
    batch_sharding,
    build_mesh,
    get_global_mesh,
    get_global_mesh_shape,
    named_sharding,
    reset_global_mesh,
    set_global_mesh,
    shard_leading_divisible,
    tree_replicated,
    tree_shard_over,
)
from .topology import (  # noqa: F401
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
    ProcessTopology,
)
