"""Isolated autotuning experiment runner.

Reference analogue: ``deepspeed/autotuning/scheduler.py`` — every
experiment runs as its own launched job so compile caches, HBM
fragmentation, and hard runtime crashes cannot leak between experiments
or kill the tuner. This is the child-process entry point: it imports the
user's factory by dotted path, builds the engine from the experiment
config, measures, and prints ONE JSON line that the parent harvests.

Factory contract (``--factory pkg.mod:fn``):
    fn(config: dict) -> (engine, make_iter)
where ``engine.train_batch(make_iter())`` runs one global batch.

Usage (normally built by ``Autotuner._run_subprocess``):
    python -m deepspeed_tpu.autotuning.runner --factory tests.x:build \
        --config exp.json [--warmup 2] [--steps 3] [--metric throughput]
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
import time


def _resolve(path: str):
    mod, _, attr = path.partition(":")
    if not attr:
        raise ValueError(f"--factory must be 'module:callable', got {path!r}")
    return getattr(importlib.import_module(mod), attr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="autotuning.runner")
    ap.add_argument("--factory", required=True)
    ap.add_argument("--config", required=True, help="experiment config JSON")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--metric", default="throughput",
                    choices=("throughput", "latency"))
    args = ap.parse_args(argv)

    with open(args.config) as fh:
        config = json.load(fh)
    factory = _resolve(args.factory)

    import jax  # after argparse: a wedged backend should not mask CLI errors
    engine, make_iter = factory(config)
    loss = None
    for _ in range(args.warmup):
        loss = engine.train_batch(make_iter())
    if loss is not None:
        float(jax.device_get(loss))            # sync before timing
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = engine.train_batch(make_iter())
    float(jax.device_get(loss))                # device_get IS the sync (axon)
    dt = (time.perf_counter() - t0) / args.steps
    val = dt if args.metric == "latency" else engine.train_batch_size() / dt
    print(json.dumps({"metric_val": val, "step_s": dt}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
