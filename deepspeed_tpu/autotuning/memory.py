"""ZeRO memory models for autotuning and user-facing estimation.

Reference analogues: ``autotuning/autotuner.py:261-285``
(get_instantiation_memory_required_per_gpu — the stage-aware params/grads/
optimizer arithmetic) and the ``estimate_zero{2,3}_model_states_mem_needs``
helpers in ``runtime/zero/utils``. The arithmetic is the published ZeRO
paper's: with Adam, fp16 params (2N) + fp16 grads (2N) + fp32 master+
momentum+variance (12N), divided over the dp world according to stage.

TPU adaptations: bf16 instead of fp16 (same 2 bytes), per-chip HBM budgets
for common TPU generations, and a mesh-aware divisor (tp shards everything
multiplicatively with dp for the states it touches).
"""

from __future__ import annotations

from typing import Dict, Optional

# per-chip HBM, bytes (usable ~95%); used when the backend can't report it
TPU_HBM_BYTES = {
    "v4": 32e9,
    "v5e": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
}


def chip_memory_bytes(default: float = 16e9) -> float:
    """Best-effort HBM size of the attached chip (falls back to `default`)."""
    try:
        import jax
        d = jax.devices()[0]
        stats = d.memory_stats() or {}
        if "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return default


def model_states_memory_per_chip(num_params: int, *, zero_stage: int,
                                 dp: int = 1, mp: int = 1,
                                 half_precision: bool = True,
                                 optimizer_factor: int = 12) -> float:
    """Bytes/chip for params+grads+optimizer states (no activations).

    optimizer_factor: bytes per param of optimizer state at fp32 master —
    12 for Adam (master + mu + nu), 8 for momentum-SGD, 4 for master-only.
    """
    p_bytes = 2 if half_precision else 4
    params = num_params * p_bytes
    grads = num_params * 4          # grads accumulated in fp32 on TPU
    optim = num_params * optimizer_factor
    if zero_stage >= 1:
        optim /= dp
    if zero_stage >= 2:
        grads /= dp
    if zero_stage >= 3:
        params /= dp
    return (params + grads + optim) / mp


def activation_memory_per_chip(*, micro_batch: int, seq_len: int,
                               hidden: int, layers: int, dp_shard: bool = False,
                               bytes_per_el: int = 2,
                               checkpoint_activations: bool = False) -> float:
    """Transformer activation estimate (per chip): the standard
    ~ B*S*H*layers*C term, C≈16 without remat, ≈2 with full remat (only
    layer inputs saved)."""
    c = 2 if checkpoint_activations else 16
    total = micro_batch * seq_len * hidden * layers * c * bytes_per_el
    return total


def max_micro_batch_for_budget(budget_bytes: float, *, num_params: int,
                               zero_stage: int, dp: int, mp: int,
                               seq_len: int, hidden: int, layers: int,
                               checkpoint_activations: bool = False) -> int:
    """Largest micro-batch whose states+activations fit in budget_bytes."""
    states = model_states_memory_per_chip(
        num_params, zero_stage=zero_stage, dp=dp, mp=mp)
    if states >= budget_bytes:
        return 0
    per_sample = activation_memory_per_chip(
        micro_batch=1, seq_len=seq_len, hidden=hidden, layers=layers,
        checkpoint_activations=checkpoint_activations)
    if per_sample <= 0:
        return 1
    return max(0, int((budget_bytes - states) // per_sample))


def estimate_zero_model_states_mem_needs(num_params: int,
                                         num_chips_per_host: int = 4,
                                         num_hosts: int = 1) -> Dict[int, float]:
    """Per-stage bytes/chip table (the reference's estimate_zero*_mem_needs
    user helpers, printed by ds_report-style tooling)."""
    world = num_chips_per_host * num_hosts
    return {stage: model_states_memory_per_chip(
        num_params, zero_stage=stage, dp=world)
        for stage in (0, 1, 2, 3)}
