"""ZeRO memory models for autotuning and user-facing estimation.

Reference analogues: ``autotuning/autotuner.py:261-285``
(get_instantiation_memory_required_per_gpu — the stage-aware params/grads/
optimizer arithmetic) and the ``estimate_zero{2,3}_model_states_mem_needs``
helpers in ``runtime/zero/utils``. The arithmetic is the published ZeRO
paper's: with Adam, fp16 params (2N) + fp16 grads (2N) + fp32 master+
momentum+variance (12N), divided over the dp world according to stage.

TPU adaptations: bf16 instead of fp16 (same 2 bytes), per-chip HBM budgets
for common TPU generations, and a mesh-aware divisor (tp shards everything
multiplicatively with dp for the states it touches).
"""

from __future__ import annotations

from typing import Dict, Optional

# per-chip HBM, bytes (usable ~95%); used when the backend can't report it
TPU_HBM_BYTES = {
    "v4": 32e9,
    "v5e": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
}


def chip_memory_bytes(default: float = 16e9) -> float:
    """Best-effort HBM size of the attached chip (falls back to `default`)."""
    try:
        import jax
        d = jax.devices()[0]
        stats = d.memory_stats() or {}
        if "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return default


def model_states_memory_per_chip(num_params: int, *, zero_stage: int,
                                 dp: int = 1, mp: int = 1,
                                 half_precision: bool = True,
                                 optimizer_factor: int = 12) -> float:
    """Bytes/chip for params+grads+optimizer states (no activations).

    optimizer_factor: bytes per param of optimizer state at fp32 master —
    12 for Adam (master + mu + nu), 8 for momentum-SGD, 4 for master-only.
    """
    p_bytes = 2 if half_precision else 4
    params = num_params * p_bytes
    grads = num_params * 4          # grads accumulated in fp32 on TPU
    optim = num_params * optimizer_factor
    if zero_stage >= 1:
        optim /= dp
    if zero_stage >= 2:
        grads /= dp
    if zero_stage >= 3:
        params /= dp
    return (params + grads + optim) / mp


def activation_memory_per_chip(*, micro_batch: int, seq_len: int,
                               hidden: int, layers: int, dp_shard: bool = False,
                               bytes_per_el: int = 2,
                               checkpoint_activations: bool = False) -> float:
    """Transformer activation estimate (per chip): the standard
    ~ B*S*H*layers*C term, C≈16 without remat, ≈2 with full remat (only
    layer inputs saved)."""
    c = 2 if checkpoint_activations else 16
    total = micro_batch * seq_len * hidden * layers * c * bytes_per_el
    return total


def max_micro_batch_for_budget(budget_bytes: float, *, num_params: int,
                               zero_stage: int, dp: int, mp: int,
                               seq_len: int, hidden: int, layers: int,
                               checkpoint_activations: bool = False) -> int:
    """Largest micro-batch whose states+activations fit in budget_bytes."""
    states = model_states_memory_per_chip(
        num_params, zero_stage=zero_stage, dp=dp, mp=mp)
    if states >= budget_bytes:
        return 0
    per_sample = activation_memory_per_chip(
        micro_batch=1, seq_len=seq_len, hidden=hidden, layers=layers,
        checkpoint_activations=checkpoint_activations)
    if per_sample <= 0:
        return 1
    return max(0, int((budget_bytes - states) // per_sample))


def host_resources(nvme_path: str = "/tmp") -> Dict[str, float]:
    """Available host DRAM and NVMe bytes (the probe behind capacity_tiers,
    shared by bench.py and ds_report so they can never disagree)."""
    import shutil
    with open("/proc/meminfo") as fh:
        host = int(fh.read().split("MemAvailable:")[1].split()[0]) * 1024
    return {"host_dram": float(host),
            "nvme_free": float(shutil.disk_usage(nvme_path).free)}


def capacity_tiers(hbm: float, host_dram: float,
                   nvme_free: float) -> Dict[str, float]:
    """Max trainable params/chip per offload tier (single source for
    bench.py case_max_params and the ds_report capacity table).

    bytes/param: pure-HBM ZeRO-1/2/3 at dp=1 keeps fp32 master+m+v+acc and
    a bf16 compute copy (18); host offload keeps bf16 params + fp32 acc on
    device (6) and master+m+v on host (12); NVMe offload mirrors bf16
    params on disk too (14 on NVMe); layer streaming
    (runtime/zero/layer_stream.py) removes the device bound — host DRAM
    holds master+m+v+grads (16), or with NVMe optimizer state only the
    grad buffers (4) while the disk holds 14. Reference analogue:
    the 13B/40B-on-one-V100 tables, docs/_posts/2021-03-08-zero3-offload.md:9."""
    hbm_usable = hbm * 0.92 - 2e9
    return {
        "hbm_only": hbm_usable / 18,
        "host_offload": min(hbm_usable / 6, host_dram * 0.9 / 12),
        "nvme_offload": min(hbm_usable / 6, nvme_free * 0.9 / 14),
        "streamed_host": host_dram * 0.9 / 16,
        "streamed_nvme": min(nvme_free * 0.9 / 14, host_dram * 0.9 / 4),
    }


# Published TPU pod-slice host topology: chips per host and host DRAM.
# v5p hosts carry 4 chips and ~448GB DRAM; the planner defaults stay
# conservative (400GB usable) so a plan that "fits" here fits in practice.
TPU_HOST = {
    "v5e": {"chips_per_host": 8, "host_dram": 256e9},
    "v5p": {"chips_per_host": 4, "host_dram": 400e9},
    "v4": {"chips_per_host": 4, "host_dram": 256e9},
}


def plan_infinity(leaf_numels, *, chips: int, hosts: int,
                  hbm_per_chip: float, host_dram_per_host: float,
                  nvme_per_host: float,
                  micro_batch: int = 1, seq_len: int = 2048,
                  hidden: int = 12288, layers: int = 96,
                  prefetch_numel: int = 0, mirror_on_nvme: bool = True,
                  headroom: float = 0.10) -> Dict[str, object]:
    """Capacity plan for the ZeRO-Infinity tier (offload_optimizer=nvme +
    offload_param=nvme): every budget is derived from what the runtime
    classes actually allocate, per tier:

      * NVMe/host   — per-leaf [master|m|v] fp32 swap files
                      (``NVMeLeafSwapper.write_init``: 12 B/param local) +
                      compute-dtype mirrors (``MirrorNVMeStore``: 2 B/param)
      * DRAM/host   — the swapper's slot windows ((1+depth) buffers of
                      3 x largest leaf shard, fp32; ``NVMeLeafSwapper``) +
                      one full set of local grad shards (the engine streams
                      ALL grad flats D2H before the leaf loop,
                      ``engine._offload_train_batch``) + one mirror staging
                      window (largest leaf shard, 2 B)
      * HBM/chip    — transient compute params (bf16 / chips; params are
                      rebuilt from mirrors and donated each step,
                      ``engine._params_resident=False``) + fp32 grad
                      accumulator shard (4 B / chips) + activations (remat)

    Leaves are dp-sharded exactly as ``_Leaf`` shards them: ceil(numel/dp)
    per rank, ranks-per-host slices per host.

    Reference analogues: the 175B/512-GPU fit tables in
    ``docs/_posts/2021-03-08-zero3-offload.md:51`` and the pipelined
    optimizer swapper (``swap_tensor/pipelined_optimizer_swapper.py:61``).
    Returns the plan dict; ``plan["fits"]`` is True only when every tier
    fits within ``1 - headroom`` of its budget."""
    from ..runtime.zero.offload import NVMeLeafSwapper

    dp = chips
    ranks_per_host = max(1, chips // hosts)
    n_global = int(sum(leaf_numels))
    shard_lens = [-(-int(n) // dp) for n in leaf_numels]       # ceil
    local_numel = sum(s * ranks_per_host for s in shard_lens)  # per host
    max_shard = max(shard_lens)

    depth = NVMeLeafSwapper.window_depth(max_shard, prefetch_numel)
    slots = NVMeLeafSwapper.slot_count(depth)
    nvme = local_numel * 12.0 + (local_numel * 2.0 if mirror_on_nvme else 0.0)
    dram = (slots * 3 * max_shard * 4.0      # swapper slot windows
            + local_numel * 4.0              # D2H grad shards (fp32)
            + max_shard * 2.0)               # mirror upload staging
    acts = activation_memory_per_chip(
        micro_batch=micro_batch, seq_len=seq_len, hidden=hidden,
        layers=layers, checkpoint_activations=True)
    hbm = n_global * 2.0 / chips + n_global * 4.0 / chips + acts

    fit = lambda used, budget: used <= budget * (1.0 - headroom)
    plan = {
        "params": n_global, "chips": chips, "hosts": hosts,
        "swap_window_slots": slots,
        "nvme_bytes_per_host": nvme, "nvme_budget": nvme_per_host,
        "dram_bytes_per_host": dram, "dram_budget": host_dram_per_host,
        "hbm_bytes_per_chip": hbm, "hbm_budget": hbm_per_chip,
        "fits_nvme": fit(nvme, nvme_per_host),
        "fits_dram": fit(dram, host_dram_per_host),
        "fits_hbm": fit(hbm, hbm_per_chip),
    }
    plan["fits"] = bool(plan["fits_nvme"] and plan["fits_dram"]
                        and plan["fits_hbm"])
    return plan


def estimate_zero_model_states_mem_needs(num_params: int,
                                         num_chips_per_host: int = 4,
                                         num_hosts: int = 1) -> Dict[int, float]:
    """Per-stage bytes/chip table (the reference's estimate_zero*_mem_needs
    user helpers, printed by ds_report-style tooling)."""
    world = num_chips_per_host * num_hosts
    return {stage: model_states_memory_per_chip(
        num_params, zero_stage=stage, dp=world)
        for stage in (0, 1, 2, 3)}


def _plan_cli(argv=None) -> int:
    """``python -m deepspeed_tpu.autotuning.memory --model gpt3_175b
    --chip v5p --chips 64`` — print the per-stage table and the Infinity
    plan for a named model on a named slice (the reference's
    estimate_zero3_model_states_mem_needs_all_live UX)."""
    import argparse
    import json
    ap = argparse.ArgumentParser(prog="deepspeed_tpu.autotuning.memory")
    ap.add_argument("--model", default="gpt3_175b",
                    help="factory name in deepspeed_tpu.models.gpt "
                         "(gpt2_125m, gpt2_1_3b, gpt_neox_20b, gpt3_175b...)")
    ap.add_argument("--chip", default="v5p", choices=sorted(TPU_HBM_BYTES))
    ap.add_argument("--chips", type=int, default=64)
    ap.add_argument("--nvme-per-host", type=float, default=3e12)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--micro-batch", type=int, default=1)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import gpt as gpt_mod
    from ..runtime.zero.partition_params import abstract_init
    factory = getattr(gpt_mod, args.model, None)
    if factory is None:
        raise SystemExit(f"unknown model {args.model!r}")
    cfg = factory()
    tree = abstract_init(gpt_mod.GPT(cfg), jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))
    numels = [int(np.prod(l.shape)) for l in jax.tree.leaves(tree)]
    n = sum(numels)
    host = TPU_HOST.get(args.chip, {"chips_per_host": 4, "host_dram": 256e9})
    hosts = max(1, -(-args.chips // host["chips_per_host"]))   # ceil
    print(f"{args.model}: {n / 1e9:.2f}B params on {args.chips}x {args.chip} "
          f"({hosts} hosts)")
    print(f"{'stage':<8}{'bytes/chip':>14}")
    for stage in (0, 1, 2, 3):
        # dp world = the chips the user asked for, not a rounded host count
        b = model_states_memory_per_chip(n, zero_stage=stage, dp=args.chips)
        fits = "OK" if b < TPU_HBM_BYTES[args.chip] * 0.9 else "OOM"
        print(f"z{stage:<7}{b / 1e9:>11.1f}GB  {fits}")
    plan = plan_infinity(
        numels, chips=args.chips, hosts=hosts,
        hbm_per_chip=TPU_HBM_BYTES[args.chip],
        host_dram_per_host=host["host_dram"],
        nvme_per_host=args.nvme_per_host,
        micro_batch=args.micro_batch, seq_len=args.seq,
        hidden=cfg.d_model, layers=cfg.num_layers,
        prefetch_numel=2 * max(-(-x // args.chips) for x in numels))
    print("infinity plan: " + json.dumps(
        {k: (round(v / 1e9, 1) if isinstance(v, float) and v > 1e6 else v)
         for k, v in plan.items()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(_plan_cli())
