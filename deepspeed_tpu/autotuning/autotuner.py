"""Autotuner: measured search over (ZeRO stage, micro-batch, mesh shape).

Reference: ``deepspeed/autotuning/autotuner.py:29`` — its loop is
(1) model-info profile run, (2) memory-model pruning of ZeRO stages,
(3) per-stage micro-batch sweep with short REAL runs harvesting a metric,
(4) emit the best config. The reference launches every experiment as a
separate cluster job through a ResourceManager (autotuning/scheduler.py)
because CUDA state can't be rebuilt in-process; on a TPU VM the XLA client
is re-usable, so experiments run IN-PROCESS — build engine, measure a few
train_batch calls, delete — which also reuses the compilation cache across
micro-batch variants of the same stage.

Search strategies (reference tuner/: GridSearchTuner, RandomTuner,
ModelBasedTuner): grid and random port directly; the xgboost cost model is
replaced by the closed-form ZeRO memory model in ``memory.py`` for pruning
plus measured refinement — on TPU the memory model is exact enough that a
learned model is unnecessary.
"""

from __future__ import annotations

import gc
import itertools
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import log_dist, logger
from .memory import (chip_memory_bytes, max_micro_batch_for_budget,
                     model_states_memory_per_chip)

METRIC_THROUGHPUT = "throughput"     # samples/sec
METRIC_LATENCY = "latency"           # sec/step (lower is better)


@dataclass
class Experiment:
    name: str
    config: Dict[str, Any]
    group: str = ""          # (stage, mesh) family — plateau stops per group
    metric_val: Optional[float] = None
    error: Optional[str] = None

    def as_record(self):
        return {"name": self.name, "config": self.config, "group": self.group,
                "metric_val": self.metric_val, "error": self.error}


@dataclass
class TuningSpace:
    """The explored axes. Values are lists; singletons pin an axis."""
    zero_stages: Sequence[int] = (0, 1, 2, 3)
    micro_batches: Sequence[int] = (1, 2, 4, 8, 16, 32)
    mesh_shapes: Sequence[Dict[str, int]] = field(default_factory=lambda: [{}])
    extra: Dict[str, Sequence] = field(default_factory=dict)


class Autotuner:
    """In-process autotuner.

    Args:
      engine_factory: callable(config_dict) -> engine with .train_batch(it)
        (typically a closure over ds.initialize with the user's model).
      data_factory: callable(micro_batch) -> iterator factory; called per
        step to produce the GAS micro-batch iterator.
      base_config: user config; tuned keys are overridden per experiment.
      num_params: for memory-model pruning (0 disables pruning).
      model_dims: dict(seq_len=, hidden=, layers=) for activation estimates.
    """

    def __init__(self, engine_factory: Callable[[dict], Any],
                 data_factory: Callable[[int], Callable[[], Any]],
                 base_config: dict, *, num_params: int = 0,
                 model_dims: Optional[dict] = None,
                 metric: str = METRIC_THROUGHPUT,
                 warmup_steps: int = 2, measure_steps: int = 3,
                 results_dir: str = "autotuning_results",
                 tuner_type: str = "gridsearch", max_experiments: int = 64,
                 early_stop_plateau: int = 2, seed: int = 0):
        self.engine_factory = engine_factory
        self.data_factory = data_factory
        self.base_config = dict(base_config)
        self.num_params = num_params
        self.model_dims = model_dims or {}
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.results_dir = results_dir
        self.tuner_type = tuner_type
        self.max_experiments = max_experiments
        self.early_stop_plateau = early_stop_plateau
        self.rng = np.random.default_rng(seed)
        self.records: List[Experiment] = []
        self.best: Optional[Experiment] = None

    # ---- pruning (the reference's fast mode, autotuner.py:222,261) ---------
    def _stage_fits(self, stage: int, dp: int, mp: int) -> bool:
        if not self.num_params:
            return True
        budget = chip_memory_bytes()
        need = model_states_memory_per_chip(
            self.num_params, zero_stage=stage, dp=dp, mp=mp)
        return need < 0.9 * budget

    def _prune_micro_batches(self, stage, dp, mp, micro_batches):
        if not (self.num_params and self.model_dims):
            return list(micro_batches)
        budget = 0.9 * chip_memory_bytes()
        cap = max_micro_batch_for_budget(
            budget, num_params=self.num_params, zero_stage=stage, dp=dp,
            mp=mp, **self.model_dims)
        kept = [m for m in micro_batches if m <= max(cap, 1)]
        dropped = sorted(set(micro_batches) - set(kept))
        if dropped:
            logger.info(f"autotuner: memory model drops micro-batches "
                        f"{dropped} at stage {stage} (cap {cap})")
        return kept

    # ---- experiment generation --------------------------------------------
    def _experiments(self, space: TuningSpace) -> List[Experiment]:
        import jax
        n_dev = len(jax.devices())
        exps = []
        for mesh in space.mesh_shapes:
            mp = mesh.get("tp", 1) * mesh.get("sp", 1)
            pp = mesh.get("pp", 1)
            dp = n_dev // max(mp * pp * mesh.get("ep", 1), 1)
            for stage in space.zero_stages:
                if not self._stage_fits(stage, dp, mp):
                    logger.info(f"autotuner: stage {stage} pruned by memory "
                                f"model at dp={dp}, mp={mp}")
                    continue
                micros = self._prune_micro_batches(
                    stage, dp, mp, space.micro_batches)
                extra_axes = sorted(space.extra)
                extra_vals = [space.extra[k] for k in extra_axes]
                for micro, *extras in itertools.product(micros, *extra_vals):
                    cfg = json.loads(json.dumps(self.base_config))
                    cfg.setdefault("zero_optimization", {})["stage"] = stage
                    cfg["train_micro_batch_size_per_gpu"] = micro
                    cfg.pop("train_batch_size", None)
                    if mesh:
                        cfg.setdefault("mesh", {}).update(mesh)
                    for k, v in zip(extra_axes, extras):
                        _set_path(cfg, k, v)
                    group = f"z{stage}" + \
                        ("_" + "_".join(f"{a}{b}" for a, b in mesh.items())
                         if mesh else "")
                    name = f"{group}_mbs{micro}" + \
                        "".join(f"_{k.split('.')[-1]}{v}"
                                for k, v in zip(extra_axes, extras))
                    exps.append(Experiment(name=name, config=cfg,
                                           group=group))
        if self.tuner_type == "random":
            order = self.rng.permutation(len(exps))
            exps = [exps[i] for i in order]
        return exps[:self.max_experiments]

    # ---- measurement -------------------------------------------------------
    def _run_experiment(self, exp: Experiment) -> Optional[float]:
        import jax
        engine = None
        try:
            engine = self.engine_factory(exp.config)
            micro = exp.config["train_micro_batch_size_per_gpu"]
            gas = exp.config.get("gradient_accumulation_steps", 1)
            make_iter = self.data_factory(micro)
            loss = None
            for _ in range(self.warmup_steps):
                loss = engine.train_batch(make_iter())
            if loss is not None:
                float(jax.device_get(loss))    # sync before timing
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                loss = engine.train_batch(make_iter())
            float(jax.device_get(loss))        # device_get IS the sync (axon)
            dt = (time.perf_counter() - t0) / self.measure_steps
            if self.metric == METRIC_LATENCY:
                return dt
            return engine.train_batch_size() / dt
        finally:
            del engine
            gc.collect()

    def _better(self, a: float, b: float) -> bool:
        return a < b if self.metric == METRIC_LATENCY else a > b

    # ---- main loop (reference tune(), autotuner.py:396) ---------------------
    def tune(self, space: Optional[TuningSpace] = None) -> Optional[dict]:
        space = space or TuningSpace()
        exps = self._experiments(space)
        log_dist(f"autotuner: {len(exps)} experiments", ranks=[0])
        os.makedirs(self.results_dir, exist_ok=True)
        plateau: Dict[str, int] = {}
        best_in_group: Dict[str, float] = {}
        stopped: set = set()
        for exp in exps:
            if exp.group in stopped:
                # micro-batch sweeps are monotone until the knee; after N
                # consecutive regressions the rest of this (stage, mesh)
                # family is skipped (reference get_plauteu_mbs,
                # autotuner.py:638)
                exp.error = "skipped: plateau early-stop"
                self.records.append(exp)
                self._write_record(exp)
                continue
            try:
                exp.metric_val = self._run_experiment(exp)
            except Exception as e:  # OOM / compile failure = infeasible point
                exp.error = f"{type(e).__name__}: {e}"
                logger.warning(f"autotuner: {exp.name} failed: {exp.error}")
            self.records.append(exp)
            self._write_record(exp)
            if exp.metric_val is not None:
                if self.best is None or self._better(exp.metric_val,
                                                     self.best.metric_val):
                    self.best = exp
                # plateau is judged against this (stage, mesh) group's OWN
                # best — a family whose first points trail another group's
                # global best may still be climbing toward its knee
                gb = best_in_group.get(exp.group)
                if gb is None or self._better(exp.metric_val, gb):
                    best_in_group[exp.group] = exp.metric_val
                    plateau[exp.group] = 0
                else:
                    plateau[exp.group] = plateau.get(exp.group, 0) + 1
                log_dist(f"autotuner: {exp.name} {self.metric}="
                         f"{exp.metric_val:.2f} (best {self.best.name})",
                         ranks=[0])
                if self.tuner_type == "gridsearch" and \
                        plateau[exp.group] >= self.early_stop_plateau:
                    stopped.add(exp.group)
        self._write_summary()
        return self.best.config if self.best else None

    def print_tuning_results(self):
        for r in self.records:
            logger.info(f"  {r.name}: {self.metric}={r.metric_val} "
                        f"{'ERROR ' + r.error if r.error else ''}")
        if self.best:
            logger.info(f"best: {self.best.name} -> {self.best.metric_val}")

    def _write_record(self, exp: Experiment):
        with open(os.path.join(self.results_dir, f"{exp.name}.json"), "w") as f:
            json.dump(exp.as_record(), f, indent=2)

    def _write_summary(self):
        with open(os.path.join(self.results_dir, "summary.json"), "w") as f:
            json.dump({
                "metric": self.metric,
                "best": self.best.as_record() if self.best else None,
                "records": [r.as_record() for r in self.records],
            }, f, indent=2)


def _set_path(cfg: dict, dotted: str, value):
    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value
