"""Autotuner: measured search over (ZeRO stage, micro-batch, mesh shape).

Reference: ``deepspeed/autotuning/autotuner.py:29`` — its loop is
(1) model-info profile run, (2) memory-model pruning of ZeRO stages,
(3) per-stage micro-batch sweep with short REAL runs harvesting a metric,
(4) emit the best config. The reference launches every experiment as a
separate cluster job through a ResourceManager (autotuning/scheduler.py)
because CUDA state can't be rebuilt in-process; on a TPU VM the XLA client
is re-usable, so experiments run IN-PROCESS — build engine, measure a few
train_batch calls, delete — which also reuses the compilation cache across
micro-batch variants of the same stage.

Search strategies (reference tuner/: GridSearchTuner, RandomTuner,
ModelBasedTuner): grid and random port directly; ``tuner_type="model"``
is the ModelBasedTuner analogue (tuner/model_based_tuner.py:158) with a
ridge regression over (stage, log-micro-batch, mesh) features standing in
for xgboost — after a bootstrap phase it measures candidates best-first by
predicted metric. The closed-form ZeRO memory model in ``memory.py`` does
hard pruning either way.

Isolation (reference autotuning/scheduler.py): ``isolation="process"``
runs every experiment through ``autotuning/runner.py`` in its own child
process with a timeout — compile caches and HBM fragmentation cannot leak
across experiments, and a hard XLA crash (OOM, sigkill) fails only that
point; the tune keeps going and still returns the measured best.
"""

from __future__ import annotations

import gc
import itertools
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import log_dist, logger
from .memory import (chip_memory_bytes, max_micro_batch_for_budget,
                     model_states_memory_per_chip)

METRIC_THROUGHPUT = "throughput"     # samples/sec
METRIC_LATENCY = "latency"           # sec/step (lower is better)


@dataclass
class Experiment:
    name: str
    config: Dict[str, Any]
    group: str = ""          # (stage, mesh) family — plateau stops per group
    metric_val: Optional[float] = None
    error: Optional[str] = None

    def as_record(self):
        return {"name": self.name, "config": self.config, "group": self.group,
                "metric_val": self.metric_val, "error": self.error}


@dataclass
class TuningSpace:
    """The explored axes. Values are lists; singletons pin an axis."""
    zero_stages: Sequence[int] = (0, 1, 2, 3)
    micro_batches: Sequence[int] = (1, 2, 4, 8, 16, 32)
    mesh_shapes: Sequence[Dict[str, int]] = field(default_factory=lambda: [{}])
    extra: Dict[str, Sequence] = field(default_factory=dict)


class Autotuner:
    """In-process autotuner.

    Args:
      engine_factory: callable(config_dict) -> engine with .train_batch(it)
        (typically a closure over ds.initialize with the user's model).
      data_factory: callable(micro_batch) -> iterator factory; called per
        step to produce the GAS micro-batch iterator.
      base_config: user config; tuned keys are overridden per experiment.
      num_params: for memory-model pruning (0 disables pruning).
      model_dims: dict(seq_len=, hidden=, layers=) for activation estimates.
    """

    def __init__(self, engine_factory: Optional[Callable[[dict], Any]],
                 data_factory: Optional[Callable[[int], Callable[[], Any]]],
                 base_config: dict, *, num_params: int = 0,
                 model_dims: Optional[dict] = None,
                 metric: str = METRIC_THROUGHPUT,
                 warmup_steps: int = 2, measure_steps: int = 3,
                 results_dir: str = "autotuning_results",
                 tuner_type: str = "gridsearch", max_experiments: int = 64,
                 early_stop_plateau: int = 2, seed: int = 0,
                 isolation: str = "inproc",
                 factory_path: Optional[str] = None,
                 experiment_timeout: float = 900.0,
                 model_bootstrap: int = 4):
        """``isolation="process"`` requires ``factory_path`` ("module:fn",
        importable in the child; fn(config) -> (engine, make_iter)) instead
        of the in-process factories. ``model_bootstrap``: measured points
        before the ``tuner_type="model"`` regressor starts ranking."""
        if isolation not in ("inproc", "process"):
            raise ValueError(f"unknown isolation {isolation!r}")
        if isolation == "process" and not factory_path:
            raise ValueError("isolation='process' requires factory_path")
        if isolation == "inproc" and (engine_factory is None
                                      or data_factory is None):
            raise ValueError(
                "isolation='inproc' requires engine_factory and "
                "data_factory (with factory_path, pass "
                "isolation='process')")
        if tuner_type not in ("gridsearch", "random", "model"):
            raise ValueError(f"unknown tuner_type {tuner_type!r}")
        self.engine_factory = engine_factory
        self.data_factory = data_factory
        self.isolation = isolation
        self.factory_path = factory_path
        self.experiment_timeout = experiment_timeout
        self.model_bootstrap = model_bootstrap
        self.base_config = dict(base_config)
        self.num_params = num_params
        self.model_dims = model_dims or {}
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.results_dir = results_dir
        self.tuner_type = tuner_type
        self.max_experiments = max_experiments
        self.early_stop_plateau = early_stop_plateau
        self.rng = np.random.default_rng(seed)
        self.records: List[Experiment] = []
        self.best: Optional[Experiment] = None

    # ---- pruning (the reference's fast mode, autotuner.py:222,261) ---------
    def _stage_fits(self, stage: int, dp: int, mp: int) -> bool:
        if not self.num_params:
            return True
        budget = chip_memory_bytes()
        need = model_states_memory_per_chip(
            self.num_params, zero_stage=stage, dp=dp, mp=mp)
        return need < 0.9 * budget

    def _prune_micro_batches(self, stage, dp, mp, micro_batches):
        if not (self.num_params and self.model_dims):
            return list(micro_batches)
        budget = 0.9 * chip_memory_bytes()
        cap = max_micro_batch_for_budget(
            budget, num_params=self.num_params, zero_stage=stage, dp=dp,
            mp=mp, **self.model_dims)
        kept = [m for m in micro_batches if m <= max(cap, 1)]
        dropped = sorted(set(micro_batches) - set(kept))
        if dropped:
            logger.info(f"autotuner: memory model drops micro-batches "
                        f"{dropped} at stage {stage} (cap {cap})")
        return kept

    # ---- experiment generation --------------------------------------------
    def _experiments(self, space: TuningSpace) -> List[Experiment]:
        import jax
        n_dev = len(jax.devices())
        exps = []
        for mesh in space.mesh_shapes:
            mp = mesh.get("tp", 1) * mesh.get("sp", 1)
            pp = mesh.get("pp", 1)
            dp = n_dev // max(mp * pp * mesh.get("ep", 1), 1)
            for stage in space.zero_stages:
                if not self._stage_fits(stage, dp, mp):
                    logger.info(f"autotuner: stage {stage} pruned by memory "
                                f"model at dp={dp}, mp={mp}")
                    continue
                micros = self._prune_micro_batches(
                    stage, dp, mp, space.micro_batches)
                extra_axes = sorted(space.extra)
                extra_vals = [space.extra[k] for k in extra_axes]
                for micro, *extras in itertools.product(micros, *extra_vals):
                    cfg = json.loads(json.dumps(self.base_config))
                    cfg.setdefault("zero_optimization", {})["stage"] = stage
                    cfg["train_micro_batch_size_per_gpu"] = micro
                    cfg.pop("train_batch_size", None)
                    if mesh:
                        cfg.setdefault("mesh", {}).update(mesh)
                    for k, v in zip(extra_axes, extras):
                        _set_path(cfg, k, v)
                    group = f"z{stage}" + \
                        ("_" + "_".join(f"{a}{b}" for a, b in mesh.items())
                         if mesh else "")
                    name = f"{group}_mbs{micro}" + \
                        "".join(f"_{k.split('.')[-1]}{v}"
                                for k, v in zip(extra_axes, extras))
                    exps.append(Experiment(name=name, config=cfg,
                                           group=group))
        if self.tuner_type == "random":
            order = self.rng.permutation(len(exps))
            exps = [exps[i] for i in order]
        return exps[:self.max_experiments]

    # ---- measurement -------------------------------------------------------
    def _run_experiment(self, exp: Experiment) -> Optional[float]:
        if self.isolation == "process":
            return self._run_subprocess(exp)
        return self._run_inproc(exp)

    def _run_subprocess(self, exp: Experiment) -> Optional[float]:
        """One experiment = one child process through autotuning/runner.py
        (reference scheduler.py job launch): a crash or hang only loses
        this point."""
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as fh:
            json.dump(exp.config, fh)
            cfg_path = fh.name
        cmd = [sys.executable, "-m", "deepspeed_tpu.autotuning.runner",
               "--factory", self.factory_path, "--config", cfg_path,
               "--warmup", str(self.warmup_steps),
               "--steps", str(self.measure_steps), "--metric", self.metric]
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=self.experiment_timeout)
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                f"experiment timed out after {self.experiment_timeout:.0f}s")
        finally:
            os.unlink(cfg_path)
        for line in reversed((p.stdout or "").strip().splitlines()):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "metric_val" in obj:
                return float(obj["metric_val"])
        tail = ((p.stderr or "").strip().splitlines() or ["no output"])[-1]
        raise RuntimeError(f"experiment rc={p.returncode}: {tail[:300]}")

    def _run_inproc(self, exp: Experiment) -> Optional[float]:
        import jax
        engine = None
        try:
            engine = self.engine_factory(exp.config)
            micro = exp.config["train_micro_batch_size_per_gpu"]
            gas = exp.config.get("gradient_accumulation_steps", 1)
            make_iter = self.data_factory(micro)
            loss = None
            for _ in range(self.warmup_steps):
                loss = engine.train_batch(make_iter())
            if loss is not None:
                float(jax.device_get(loss))    # sync before timing
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                loss = engine.train_batch(make_iter())
            float(jax.device_get(loss))        # device_get IS the sync (axon)
            dt = (time.perf_counter() - t0) / self.measure_steps
            if self.metric == METRIC_LATENCY:
                return dt
            return engine.train_batch_size() / dt
        finally:
            del engine
            gc.collect()

    def _better(self, a: float, b: float) -> bool:
        return a < b if self.metric == METRIC_LATENCY else a > b

    # ---- cost model (reference tuner/model_based_tuner.py:158) -------------
    @staticmethod
    def _features(exp: Experiment) -> np.ndarray:
        cfg = exp.config
        stage = float(cfg.get("zero_optimization", {}).get("stage", 0))
        micro = float(cfg.get("train_micro_batch_size_per_gpu", 1))
        mesh = cfg.get("mesh", {}) or {}
        lm = np.log2(max(micro, 1.0))
        return np.array([1.0, stage, lm, lm * lm, stage * lm,
                         float(mesh.get("pp", 1)), float(mesh.get("tp", 1)),
                         float(mesh.get("ep", 1))])

    def _fit_predict(self, measured: List[Experiment],
                     candidates: List[Experiment]) -> np.ndarray:
        """Ridge regression metric predictor (xgboost stand-in: the space
        is small and smooth in (stage, log mbs), so a quadratic linear
        model ranks candidates well after a few bootstrap points)."""
        X = np.stack([self._features(e) for e in measured])
        y = np.array([e.metric_val for e in measured])
        lam = 1e-3
        w = np.linalg.solve(X.T @ X + lam * np.eye(X.shape[1]), X.T @ y)
        return np.stack([self._features(e) for e in candidates]) @ w

    def _measure(self, exp: Experiment) -> None:
        """Run + record one experiment (shared by both tune loops)."""
        try:
            exp.metric_val = self._run_experiment(exp)
        except Exception as e:   # OOM / crash / timeout = infeasible point
            exp.error = f"{type(e).__name__}: {e}"
            logger.warning(f"autotuner: {exp.name} failed: {exp.error}")
        self.records.append(exp)
        self._write_record(exp)
        if exp.metric_val is not None:
            if self.best is None or self._better(exp.metric_val,
                                                 self.best.metric_val):
                self.best = exp
            log_dist(f"autotuner: {exp.name} {self.metric}="
                     f"{exp.metric_val:.2f} (best {self.best.name})",
                     ranks=[0])

    def _tune_model_based(self, exps: List[Experiment]) -> Optional[dict]:
        """Bootstrap a few points, then fit-predict-measure best-first;
        stop after `early_stop_plateau` consecutive non-improvements and
        prune the rest by predicted rank."""
        todo = list(exps)
        for exp in todo[:self.model_bootstrap]:
            self._measure(exp)
        todo = todo[self.model_bootstrap:]
        misses = 0
        while todo:
            measured = [r for r in self.records if r.metric_val is not None]
            if len(measured) < 2:     # model unfittable; fall back to order
                pick = todo.pop(0)
            else:
                preds = self._fit_predict(measured, todo)
                order = np.argsort(preds)
                idx = int(order[0 if self.metric == METRIC_LATENCY
                                else -1])
                pick = todo.pop(idx)
            prev_best = self.best.metric_val if self.best else None
            self._measure(pick)
            if pick.metric_val is not None:
                # like the grid loop, only MEASURED regressions count as
                # plateau misses; crashed/OOM points are infeasible-space
                # probes (capped by max_experiments), not evidence the
                # feasible region has stopped improving
                improved = (prev_best is None or
                            self._better(pick.metric_val, prev_best))
                misses = 0 if improved else misses + 1
            if misses >= self.early_stop_plateau:
                for exp in todo:
                    exp.error = "skipped: cost-model prune"
                    self.records.append(exp)
                    self._write_record(exp)
                break
        self._write_summary()
        return self.best.config if self.best else None

    # ---- main loop (reference tune(), autotuner.py:396) ---------------------
    def tune(self, space: Optional[TuningSpace] = None) -> Optional[dict]:
        space = space or TuningSpace()
        exps = self._experiments(space)
        log_dist(f"autotuner: {len(exps)} experiments", ranks=[0])
        os.makedirs(self.results_dir, exist_ok=True)
        if self.tuner_type == "model":
            return self._tune_model_based(exps)
        plateau: Dict[str, int] = {}
        best_in_group: Dict[str, float] = {}
        stopped: set = set()
        for exp in exps:
            if exp.group in stopped:
                # micro-batch sweeps are monotone until the knee; after N
                # consecutive regressions the rest of this (stage, mesh)
                # family is skipped (reference get_plauteu_mbs,
                # autotuner.py:638)
                exp.error = "skipped: plateau early-stop"
                self.records.append(exp)
                self._write_record(exp)
                continue
            self._measure(exp)
            if exp.metric_val is not None:
                # plateau is judged against this (stage, mesh) group's OWN
                # best — a family whose first points trail another group's
                # global best may still be climbing toward its knee
                gb = best_in_group.get(exp.group)
                if gb is None or self._better(exp.metric_val, gb):
                    best_in_group[exp.group] = exp.metric_val
                    plateau[exp.group] = 0
                else:
                    plateau[exp.group] = plateau.get(exp.group, 0) + 1
                if self.tuner_type == "gridsearch" and \
                        plateau[exp.group] >= self.early_stop_plateau:
                    stopped.add(exp.group)
        self._write_summary()
        return self.best.config if self.best else None

    def print_tuning_results(self):
        for r in self.records:
            logger.info(f"  {r.name}: {self.metric}={r.metric_val} "
                        f"{'ERROR ' + r.error if r.error else ''}")
        if self.best:
            logger.info(f"best: {self.best.name} -> {self.best.metric_val}")

    def _write_record(self, exp: Experiment):
        with open(os.path.join(self.results_dir, f"{exp.name}.json"), "w") as f:
            json.dump(exp.as_record(), f, indent=2)

    def _write_summary(self):
        with open(os.path.join(self.results_dir, "summary.json"), "w") as f:
            json.dump({
                "metric": self.metric,
                "best": self.best.as_record() if self.best else None,
                "records": [r.as_record() for r in self.records],
            }, f, indent=2)


def _set_path(cfg: dict, dotted: str, value):
    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value
