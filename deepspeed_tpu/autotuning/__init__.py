"""Autotuning (reference: deepspeed/autotuning/autotuner.py): memory-model
pruning + measured in-process sweeps over (ZeRO stage, micro-batch, mesh
shape), emitting the best config."""

from .autotuner import (Autotuner, Experiment, TuningSpace,
                        METRIC_LATENCY, METRIC_THROUGHPUT)
from .memory import (activation_memory_per_chip, chip_memory_bytes,
                     estimate_zero_model_states_mem_needs,
                     max_micro_batch_for_budget,
                     model_states_memory_per_chip)
from .serving_tuner import (METRIC_TOKENS_PER_S, ServingCapacityTuner,
                            ServingTuningSpace, TUNED_SCHEMA,
                            tune_serving_capacity)

__all__ = ["Autotuner", "TuningSpace", "Experiment", "METRIC_THROUGHPUT",
           "METRIC_LATENCY", "model_states_memory_per_chip",
           "activation_memory_per_chip", "max_micro_batch_for_budget",
           "estimate_zero_model_states_mem_needs", "chip_memory_bytes",
           "ServingCapacityTuner", "ServingTuningSpace",
           "tune_serving_capacity", "METRIC_TOKENS_PER_S", "TUNED_SCHEMA"]
