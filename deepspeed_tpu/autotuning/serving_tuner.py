"""Serving capacity autotuner: measured search over the serving knobs.

The training ``Autotuner`` sweeps (ZeRO stage, micro-batch, mesh); this
reuses its experiment loop (records, plateau early-stop, best tracking,
result files) but swaps the axes for the serving engine's capacity
knobs — KV block size, fused decode-chunk ``K``, speculative ``spec_k``,
fused-prefill chunk ``C``, and the tiered-KV DRAM watermark — and the
measurement for a short REAL serving run (warm pass + timed pass over a
fixed workload, tokens/s harvested).

Each experiment also records the engine's KV HBM footprint
(``arena_report()``), so the output is not a single winner but a
**Pareto frontier** over (tokens/s up, HBM bytes down): the all-HBM
corner and the tier-heavy corner are both kept if neither dominates.
``write_tuned_config`` emits the frontier as ``dstpu-tuned-v1`` JSON,
which ``ServingEngine(tuned_config=...)`` loads directly (it picks
``best``, or the max-throughput frontier point).

Usage::

    tuner = ServingCapacityTuner(engine_factory, workload_factory)
    tuner.tune(ServingTuningSpace(block_sizes=(8, 16),
                                  decode_chunks=(4, 8)))
    tuner.write_tuned_config("tuned.json")
    serving = ServingEngine(engine=eng, tuned_config="tuned.json")

or the one-call convenience ``tune_serving_capacity(base_engine, ...)``.
"""

from __future__ import annotations

import gc
import itertools
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import logger
from .autotuner import Autotuner, Experiment

#: schema tag of the emitted tuned-config JSON (consumed by
#: ``ServingEngine(tuned_config=...)``).
TUNED_SCHEMA = "dstpu-tuned-v1"

METRIC_TOKENS_PER_S = "tokens_per_s"

#: axis key -> short tag used in experiment names
_ABBREV = {"kv_block_size": "bs", "decode_chunk": "k", "spec_k": "sk",
           "prefill_chunk": "c", "tier_dram_bytes": "dram"}


@dataclass
class ServingTuningSpace:
    """Explored serving axes. Values are lists; singletons pin an axis.

    ``spec_ks`` uses 0 for "speculation off"; ``tier_dram_bytes`` uses
    ``None`` for "tiering off" (pure HBM) — mixing None with byte
    budgets sweeps the tier watermark against the all-HBM baseline.
    """
    block_sizes: Sequence[int] = (8, 16)
    decode_chunks: Sequence[int] = (4, 8)
    spec_ks: Sequence[int] = (0,)
    prefill_chunks: Sequence[int] = (16,)
    tier_dram_bytes: Sequence[Optional[int]] = (None,)


class ServingCapacityTuner(Autotuner):
    """Grid/random tuner over serving capacity knobs.

    Args:
      engine_factory: callable(config_dict) -> ``ServingEngine``. The
        config dict carries the swept keys (``kv_block_size``,
        ``decode_chunk``, ``spec_k``, ``prefill_chunk``,
        ``tier_dram_bytes``) merged over ``base_config``.
      workload_factory: callable(config_dict) -> (prompts,
        max_new_tokens); called per experiment so the workload can adapt
        to the config (it usually ignores it).
      base_config: keys merged under every experiment's config.
    """

    def __init__(self, engine_factory: Callable[[dict], Any],
                 workload_factory: Callable[[dict], Any],
                 base_config: Optional[dict] = None, *,
                 warmup_runs: int = 1, **kw):
        kw.setdefault("metric", METRIC_TOKENS_PER_S)
        kw.setdefault("results_dir", "serving_tuning_results")
        super().__init__(engine_factory, workload_factory,
                         base_config or {}, warmup_steps=warmup_runs,
                         **kw)
        if self.tuner_type == "model":
            raise ValueError(
                "serving tuner supports tuner_type 'gridsearch' or "
                "'random' (the training cost model's features do not "
                "transfer)")
        #: per-experiment side data keyed by name: hbm_bytes, wall_s, ...
        self._aux: Dict[str, Dict[str, Any]] = {}

    # ---- experiment generation --------------------------------------------
    def _experiments(self, space) -> List[Experiment]:
        axes = [("kv_block_size", space.block_sizes),
                ("decode_chunk", space.decode_chunks),
                ("spec_k", space.spec_ks),
                ("prefill_chunk", space.prefill_chunks),
                ("tier_dram_bytes", space.tier_dram_bytes)]
        exps = []
        for vals in itertools.product(*(v for _, v in axes)):
            cfg = json.loads(json.dumps(self.base_config))
            cfg.update({k: v for (k, _), v in zip(axes, vals)})
            # plateau groups by block size: the decode_chunk sweep within
            # one block size is the monotone-until-the-knee family
            group = f"bs{cfg['kv_block_size']}"
            name = "_".join(
                f"{_ABBREV[k]}{'off' if v is None else v}"
                for (k, _), v in zip(axes, vals))
            exps.append(Experiment(name=name, config=cfg, group=group))
        if self.tuner_type == "random":
            order = self.rng.permutation(len(exps))
            exps = [exps[i] for i in order]
        return exps[:self.max_experiments]

    # ---- measurement -------------------------------------------------------
    def _run_inproc(self, exp: Experiment) -> Optional[float]:
        serving = None
        try:
            serving = self.engine_factory(exp.config)
            prompts, max_new = self.data_factory(exp.config)
            prompts = [np.asarray(p, np.int32) for p in prompts]
            for _ in range(self.warmup_steps):
                serving.run([p.copy() for p in prompts],
                            max_new_tokens=max_new)
            t0 = time.perf_counter()
            results = serving.run([p.copy() for p in prompts],
                                  max_new_tokens=max_new)
            dt = time.perf_counter() - t0
            tokens = sum(len(r.tokens) for r in results)
            rep = serving.kv.arena_report()
            hbm = int(rep.get("kv_bytes") or rep.get("arena_bytes") or 0)
            self._aux[exp.name] = {
                "hbm_bytes": hbm,
                "wall_s": dt,
                "tokens": tokens,
                "tiers": rep.get("tiers"),
            }
            return tokens / max(dt, 1e-9)
        finally:
            close = getattr(serving, "close", None)
            if close is not None:
                close()
            del serving
            gc.collect()

    # ---- Pareto frontier ---------------------------------------------------
    def pareto_points(self) -> List[Dict[str, Any]]:
        """Measured points not dominated on (tokens/s up, HBM bytes
        down), sorted by ascending HBM footprint."""
        pts = []
        for r in self.records:
            if r.metric_val is None:
                continue
            aux = self._aux.get(r.name, {})
            pts.append({"name": r.name, "config": r.config,
                        "tokens_per_s": float(r.metric_val),
                        "hbm_bytes": int(aux.get("hbm_bytes", 0))})
        frontier = [p for p in pts if not any(
            q["tokens_per_s"] >= p["tokens_per_s"]
            and q["hbm_bytes"] <= p["hbm_bytes"]
            and (q["tokens_per_s"] > p["tokens_per_s"]
                 or q["hbm_bytes"] < p["hbm_bytes"])
            for q in pts)]
        frontier.sort(key=lambda p: (p["hbm_bytes"], -p["tokens_per_s"]))
        return frontier

    def tuned_config_doc(self) -> Dict[str, Any]:
        frontier = self.pareto_points()
        best = max(frontier, key=lambda p: p["tokens_per_s"]) \
            if frontier else None
        return {
            "schema": TUNED_SCHEMA,
            "metric": self.metric,
            "best": best,
            "pareto": frontier,
            "records": [r.as_record() for r in self.records],
        }

    def write_tuned_config(self, path: str) -> Dict[str, Any]:
        doc = self.tuned_config_doc()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
        logger.info(f"serving tuner: wrote {len(doc['pareto'])} Pareto "
                    f"point(s) to {path}")
        return doc

    # ---- main loop ---------------------------------------------------------
    def tune(self, space: Optional[ServingTuningSpace] = None):
        space = space or ServingTuningSpace()
        best = super().tune(space)
        self.write_tuned_config(
            os.path.join(self.results_dir, "tuned_config.json"))
        return best


def tune_serving_capacity(base_engine, *, n_requests: int = 4,
                          prompt_len: int = 16, max_new_tokens: int = 8,
                          space: Optional[ServingTuningSpace] = None,
                          out: Optional[str] = None, seed: int = 0,
                          **tuner_kw) -> Dict[str, Any]:
    """One-call tune over a base ``InferenceEngine``: paged serving
    engines built per config (tiered when the config carries a
    ``tier_dram_bytes`` budget; speculative engines run the per-token
    loop like the production spec config), a fixed mixed-length
    workload, ``dstpu-tuned-v1`` JSON returned (and written to ``out``).
    """
    from ..serving import ServingEngine

    vocab = base_engine.module.cfg.vocab_size
    rng = np.random.default_rng(seed)
    lens = rng.integers(min(4, prompt_len), prompt_len + 1, n_requests)
    lens[0] = prompt_len
    prompts = [rng.integers(0, vocab, (int(n),)).astype(np.int32)
               for n in lens]

    def engine_factory(cfg):
        kw = dict(engine=base_engine, max_batch=n_requests,
                  max_prompt_len=prompt_len, max_queue=n_requests,
                  paged=True,
                  kv_block_size=int(cfg.get("kv_block_size", 16)),
                  decode_chunk=int(cfg.get("decode_chunk", 8)),
                  prefill_chunk=int(cfg.get("prefill_chunk", 16)))
        if cfg.get("spec_k"):
            kw.update(speculative=True, spec_k=int(cfg["spec_k"]),
                      decode_chunk=1)
        if cfg.get("tier_dram_bytes") is not None:
            kw.update(tiered_kv=True,
                      tier_dram_bytes=int(cfg["tier_dram_bytes"]))
        return ServingEngine(**kw)

    def workload_factory(cfg):
        return [p.copy() for p in prompts], max_new_tokens

    tuner = ServingCapacityTuner(engine_factory, workload_factory,
                                 seed=seed, **tuner_kw)
    tuner.tune(space or ServingTuningSpace())
    if out is not None:
        return tuner.write_tuned_config(out)
    return tuner.tuned_config_doc()
