"""Sharded Mixture-of-Experts: gating + all-to-all dispatch, TPU-native.

Reference analogue: ``deepspeed/moe/sharded_moe.py`` — ``top1gating`` (:178),
``top2gating`` (:279), ``TopKGate`` (:352), ``MOELayer`` (:440) with its
einsum dispatch -> all-to-all -> local experts -> all-to-all -> einsum
combine pipeline (:488-561).

TPU-native redesign:

  * The reference's ``_AllToAll`` autograd wrapper over
    ``dist.all_to_all_single`` disappears: the dispatched ``[E, C, M]``
    tensor is simply sharding-constrained to the ``ep`` mesh axis, and XLA
    emits the all-to-all (forward AND backward) when the layout changes from
    token-sharded to expert-sharded. Differentiation is automatic.
  * Capacity is a static Python int (shapes are static under jit); the
    reference's dynamic no-drop path (``drop_tokens=False`` -> allreduce MAX
    of counts, sharded_moe.py:215-218) becomes capacity = num_tokens, which
    drops nothing by construction.
  * Randomness (RSample noisy gating, Random Token Selection) uses explicit
    JAX PRNG keys instead of cached torch distribution samplers
    (sharded_moe.py:32-81).

einsum dimension legend (GShard, arXiv:2006.16668): (s)equence/tokens,
(e)xpert, (m)odel dim, (c)apacity.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel import mesh as mesh_lib


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Static per-expert capacity (reference ``_capacity``,
    sharded_moe.py:158-166)."""
    cap = math.ceil(num_tokens / num_experts) * capacity_factor
    cap = int(math.ceil(cap))
    if cap < min_capacity:
        cap = int(min_capacity)
    return min(cap, num_tokens)


def _keep_top_capacity(mask: jnp.ndarray, priority: jnp.ndarray,
                       capacity: int) -> jnp.ndarray:
    """Keep at most ``capacity`` tokens per expert, choosing the tokens with
    the highest ``priority`` (reference ``_top_idx`` + scatter,
    sharded_moe.py:168-246). mask/priority: [S, E] -> pruned mask [S, E]."""
    s, e = mask.shape
    # top-k over the token dim for every expert
    _, top_idx = jax.lax.top_k(priority.T, capacity)          # [E, C]
    keep = jnp.zeros((e, s), dtype=mask.dtype)
    keep = keep.at[jnp.arange(e)[:, None], top_idx].set(1)
    return mask * keep.T


def top1gating(logits: jnp.ndarray,
               capacity_factor: float,
               min_capacity: int,
               rng: Optional[jax.Array] = None,
               used_token: Optional[jnp.ndarray] = None,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               use_rts: bool = True
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 gating (Switch-style). logits: [S, E] fp32.

    Returns (l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C] bool,
    exp_counts [E]). Mirrors reference top1gating (sharded_moe.py:178-276).
    """
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=1)

    capacity = _capacity(s, e, capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = s  # statically large enough to never drop

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        noisy = logits + jax.random.gumbel(sub, logits.shape, logits.dtype)
        indices1 = jnp.argmax(noisy, axis=1)
    else:
        indices1 = jnp.argmax(gates, axis=1)
    mask1 = jax.nn.one_hot(indices1, e, dtype=jnp.int32)

    if used_token is not None:
        mask1 = mask1 * used_token.astype(jnp.int32)[:, None]

    exp_counts = jnp.sum(mask1, axis=0)

    # load-balancing loss (GShard eq.; reference :220-223)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * e

    # Random Token Selection: random priority inside each over-capacity
    # expert instead of sequence order (reference :225-246)
    if use_rts and rng is not None:
        rng, sub = jax.random.split(rng)
        priority = mask1.astype(jnp.float32) * jax.random.uniform(
            sub, mask1.shape)
    else:
        priority = mask1.astype(jnp.float32)
    mask1 = _keep_top_capacity(mask1, priority, capacity)

    locations1 = jnp.cumsum(mask1, axis=0) - 1                 # [S, E]
    locations1_s = jnp.sum(locations1 * mask1, axis=1)         # [S]

    gates_masked = gates * mask1.astype(gates.dtype)
    locations1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=gates.dtype)
    combine_weights = jnp.einsum("se,sc->sec", gates_masked, locations1_sc)
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2gating(logits: jnp.ndarray,
               capacity_factor: float,
               min_capacity: int,
               rng: Optional[jax.Array] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-2 gating (GShard). logits: [S, E] fp32. Second expert chosen by
    the Gumbel-max trick over the remaining logits (reference top2gating,
    sharded_moe.py:279-349)."""
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=1)
    capacity = _capacity(s, e, capacity_factor * 2.0, min_capacity)

    indices1 = jnp.argmax(gates, axis=1)
    mask1 = jax.nn.one_hot(indices1, e, dtype=jnp.int32)

    if rng is not None:
        rng, sub = jax.random.split(rng)
        noisy = logits + jax.random.gumbel(sub, logits.shape, logits.dtype)
    else:
        noisy = logits
    masked = jnp.where(mask1 > 0, -jnp.inf, noisy)
    indices2 = jnp.argmax(masked, axis=1)
    mask2 = jax.nn.one_hot(indices2, e, dtype=jnp.int32)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1
    locations2 = locations2 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1, axis=0)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.mean(me * ce) * e * e

    mask1 = mask1 * (locations1 < capacity).astype(jnp.int32)
    mask2 = mask2 * (locations2 < capacity).astype(jnp.int32)

    locations1_s = jnp.sum(locations1 * mask1, axis=1)
    locations2_s = jnp.sum(locations2 * mask2, axis=1)

    mask1_f = mask1.astype(gates.dtype)
    mask2_f = mask2.astype(gates.dtype)
    gates1_s = jnp.einsum("se,se->s", gates, mask1_f)
    gates2_s = jnp.einsum("se,se->s", gates, mask2_f)
    denom = jnp.clip(gates1_s + gates2_s, jnp.finfo(gates.dtype).eps, None)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    gates1 = jnp.einsum("s,se->se", gates1_s, mask1_f)
    gates2 = jnp.einsum("s,se->se", gates2_s, mask2_f)
    loc1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=gates.dtype)
    loc2_sc = jax.nn.one_hot(locations2_s, capacity, dtype=gates.dtype)
    combine_weights = (jnp.einsum("se,sc->sec", gates1, loc1_sc)
                       + jnp.einsum("se,sc->sec", gates2, loc2_sc))
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


class TopKGate(nn.Module):
    """Gate network: fp32 linear -> top-k gating (reference TopKGate,
    sharded_moe.py:352-437). k in {1, 2}."""
    model_dim: int
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 8
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, used_token=None,
                 deterministic: bool = True):
        if self.k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported.")
        # gate math is always fp32 (reference :406-409)
        x = tokens.astype(jnp.float32)
        rng = None
        if not deterministic and self.has_rng("gating"):
            rng = self.make_rng("gating")
        if (self.noisy_gate_policy == "Jitter" and not deterministic
                and rng is not None):
            rng, sub = jax.random.split(rng)
            x = x * jax.random.uniform(sub, x.shape, jnp.float32, 0.99, 1.01)
        logits = nn.Dense(self.num_experts, use_bias=False,
                          dtype=jnp.float32, param_dtype=jnp.float32,
                          name="wg")(x)
        cf = self.capacity_factor if not deterministic else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(
                logits, cf, self.min_capacity, rng=rng, used_token=used_token,
                noisy_gate_policy=self.noisy_gate_policy if not deterministic else None,
                drop_tokens=self.drop_tokens, use_rts=self.use_rts)
        return top2gating(logits, cf, self.min_capacity, rng=rng)


def _ep_constraint(x: jnp.ndarray) -> jnp.ndarray:
    """Constrain an [E, C, M] tensor's expert dim to the ``ep`` mesh axis —
    this is where XLA emits the dispatch/combine all-to-all (the reference's
    explicit ``_AllToAll.apply``, sharded_moe.py:92-105)."""
    try:
        mesh = mesh_lib.get_constraint_mesh()
    except Exception:
        return x
    if "ep" not in mesh.shape or x.shape[0] % max(mesh.shape["ep"], 1):
        return x
    from jax.sharding import PartitionSpec as P
    spec = P("ep", *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))


class MOELayer(nn.Module):
    """Dispatch -> experts -> combine (reference MOELayer.forward,
    sharded_moe.py:488-561). ``experts`` maps [E, C, M] -> [E, C, M] with
    expert-stacked params (see moe/experts.py)."""
    gate: TopKGate
    experts: nn.Module

    @nn.compact
    def __call__(self, x: jnp.ndarray, used_token=None,
                 deterministic: bool = True):
        d_model = x.shape[-1]
        tokens = x.reshape(-1, d_model)                        # [S, M]
        l_aux, combine, dispatch, exp_counts = self.gate(
            tokens, used_token, deterministic)

        dispatched = jnp.einsum("sec,sm->ecm",
                                dispatch.astype(x.dtype), tokens)
        dispatched = _ep_constraint(dispatched)
        expert_out = self.experts(dispatched)                  # [E, C, M]
        expert_out = _ep_constraint(expert_out)
        out = jnp.einsum("sec,ecm->sm",
                         combine.astype(x.dtype), expert_out)
        return out.reshape(x.shape), l_aux, exp_counts
