"""Mixture-of-Experts subsystem (reference: ``deepspeed/moe/``)."""

from .experts import Experts
from .layer import MoE
from .sharded_moe import MOELayer, TopKGate, top1gating, top2gating
from .utils import (count_moe_params, is_moe_param, is_moe_param_path,
                    moe_param_mask, split_params_into_shared_and_expert)
