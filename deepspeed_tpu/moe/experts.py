"""Expert bank: one module template, num_experts parameter copies.

Reference analogue: ``deepspeed/moe/experts.py:9-34`` — deep-copies the
expert module ``num_local_experts`` times and stamps ``allreduce=False`` /
``group_name`` on every expert parameter so the engine reduces them over the
expert-data-parallel group instead of the dp group (engine.py:2171-2186).

TPU-native: the copies are one ``nn.vmap`` lift — params get a stacked
leading expert dim [E, ...] whose path contains ``experts``; the sharding
rules (runtime/sharding.py) shard that dim over the ``ep`` mesh axis, and
GSPMD reduces expert grads only over the axes they are replicated on
(the expert-data-parallel semantics, for free).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class _ApplyExpert(nn.Module):
    inner: nn.Module

    @nn.compact
    def __call__(self, x):
        out = self.inner(x)
        if isinstance(out, tuple):
            out = out[0]
        return out


class Experts(nn.Module):
    """Applies ``num_experts`` independent copies of ``expert`` to the
    leading dim of an [E, C, M] tensor."""
    expert: nn.Module
    num_experts: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if x.shape[0] != self.num_experts:
            raise ValueError(
                f"expected leading expert dim {self.num_experts}, "
                f"got shape {x.shape}")
        VmappedExpert = nn.vmap(
            _ApplyExpert,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=0, out_axes=0,
            metadata_params={nn.PARTITION_NAME: "experts"},
        )
        # clone the template so flax does not "adopt" the shared instance
        # into the caller's scope — the stacked params must live under
        # .../experts/ (the path the sharding rules key on)
        return VmappedExpert(inner=self.expert.clone(), name="experts")(x)
