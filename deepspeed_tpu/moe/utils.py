"""MoE parameter utilities.

Reference analogue: ``deepspeed/moe/utils.py`` — ``is_moe_param`` (:18) keys
on the ``allreduce=False`` attribute stamped by Experts;
``split_params_into_different_moe_groups_for_optimizer`` (:62) splits
optimizer param groups into shared vs per-expert-group params so the engine
can reduce expert grads over the expert-data-parallel group
(engine.py:2171-2186).

TPU-native: params are a pytree; MoE-ness is a property of the parameter
*path* (the Experts lift names its stacked params ``experts/...``), and grad
reduction scope is decided by GSPMD from shardings — so the utilities here
are pure tree-mask helpers used for weight decay masks, checkpoint layout,
and param counting.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..runtime.sharding import path_str


def is_moe_param_path(path: str) -> bool:
    return "experts" in path.split("/") or "/experts/" in f"/{path}/"


def is_moe_param(path) -> bool:
    """path: a flax tree path tuple or a '/'-joined string."""
    if not isinstance(path, str):
        path = path_str(path)
    return is_moe_param_path(path)


def moe_param_mask(params) -> Any:
    """Pytree of bools: True for expert params. Usable as an optax mask."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: is_moe_param(p), params)


def split_params_into_shared_and_expert(params) -> Tuple[dict, dict]:
    """Two flat ``{path: leaf}`` dicts: shared params and expert params —
    the analogue of the reference's optimizer param-group split
    (moe/utils.py:62-119). Flat dicts (not pruned pytrees) so callers can
    zip/merge them without treedef mismatches; for masked optax transforms
    use ``moe_param_mask`` instead."""
    shared, expert = {}, {}
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in leaves:
        (expert if is_moe_param(path) else shared)[path_str(path)] = leaf
    return shared, expert


def count_moe_params(params) -> Tuple[int, int]:
    """(shared_count, expert_count) over leaves."""
    shared = expert = 0
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in leaves:
        n = int(jnp.size(leaf))
        if is_moe_param(path):
            expert += n
        else:
            shared += n
    return shared, expert
