"""User-facing MoE layer.

Reference analogue: ``deepspeed/moe/layer.py:18-131`` — wraps an expert
module with a TopKGate + MOELayer, optionally as a Residual MoE
(arXiv:2201.05596) with a learned 2-way coefficient mix. The reference's
lazy expert-parallel process-group creation (``_create_process_groups``,
layer.py:88-104) is unnecessary here: expert parallelism is the ``ep`` mesh
axis, fixed at mesh construction.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .experts import Experts, _ApplyExpert
from .sharded_moe import MOELayer, TopKGate


class MoE(nn.Module):
    """Mixture-of-Experts layer. ``__call__(hidden [.., M])`` returns
    ``(output, l_aux, exp_counts)`` like the reference (layer.py:106-131)."""
    hidden_size: int
    expert: nn.Module
    num_experts: int = 1
    ep_size: int = 1                 # kept for API parity; mesh governs EP
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    use_residual: bool = False
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, hidden_states: jnp.ndarray, used_token=None,
                 deterministic: bool = True):
        assert self.noisy_gate_policy in (None, "None", "Jitter", "RSample"), \
            f"Unsupported noisy_gate_policy: {self.noisy_gate_policy}"
        gate = TopKGate(
            model_dim=self.hidden_size,
            num_experts=self.num_experts,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=None if self.noisy_gate_policy == "None"
            else self.noisy_gate_policy,
            drop_tokens=self.drop_tokens,
            use_rts=self.use_rts,
            name="gate")
        moe = MOELayer(
            gate=gate,
            experts=Experts(expert=self.expert,
                            num_experts=self.num_experts),
            name="deepspeed_moe")
        output, l_aux, exp_counts = moe(hidden_states, used_token,
                                        deterministic)
        if self.use_residual:
            # Residual MoE: learned softmax mix of expert path and a dense
            # MLP path (reference layer.py:117-130). Clone the template so
            # the dense path gets its own (unstacked) params.
            mlp_out = _ApplyExpert(inner=self.expert.clone(),
                                   name="mlp")(hidden_states)
            coef = nn.Dense(2, dtype=hidden_states.dtype,
                            name="coefficient")(hidden_states)
            coef = jax.nn.softmax(coef, axis=-1)
            output = output * coef[..., 0:1] + mlp_out * coef[..., 1:]
        return output, l_aux, exp_counts
