#!/usr/bin/env python
"""Repo-root entry point for the serving benchmark.

Thin wrapper over ``deepspeed_tpu.benchmarks.serving_bench`` so the
canonical invocation from a checkout is simply::

    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py --n-requests 8
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.benchmarks.serving_bench import main  # noqa: E402

if __name__ == "__main__":
    main()
