#!/usr/bin/env python
"""Repo-root entry point for the fleet simulator benchmark.

Thin wrapper over ``deepspeed_tpu.benchmarks.fleetsim_bench`` so the
canonical invocation from a checkout is simply::

    JAX_PLATFORMS=cpu python benchmarks/fleetsim_bench.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.benchmarks.fleetsim_bench import main  # noqa: E402

if __name__ == "__main__":
    main()
