// SIMD CPU optimizers for host-offloaded ZeRO.
//
// Reference analogue: csrc/adam/cpu_adam.cpp (AVX256/AVX512 tiled Adam over
// host-pinned fp32 master params, csrc/includes/cpu_adam.h TILE loop) and
// csrc/adagrad/cpu_adagrad.cpp. TPU-native differences: no CUDA stream
// copy-back (the Python side ships updated shards to the chip via a single
// device_put), and vectorization is OpenMP-parallel loops with
// compiler-vectorized (AVX2 via -march) inner bodies plus an explicit
// AVX2 path for the hot fused Adam update.
//
// C ABI (loaded via ctypes, see deepspeed_tpu/ops/op_builder.py):
//   ds_adam_step      — fused Adam/AdamW over flat fp32 arrays
//   ds_adagrad_step   — fused Adagrad
//   ds_adam_step_bf16 — Adam on fp32 master with extra bf16 param mirror
//                       (the fp16-copy the reference writes back to GPU)

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// Fused Adam/AdamW step on flat fp32 buffers.
//   adamw != 0 -> decoupled weight decay (AdamW); else L2-into-grad Adam.
//   step is the 1-based optimizer step for bias correction.
void ds_adam_step(float* params, const float* grads, float* exp_avg,
                  float* exp_avg_sq, int64_t n, float lr, float beta1,
                  float beta2, float eps, float weight_decay, int adamw,
                  int64_t step) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);

#pragma omp parallel
    {
#if defined(__AVX2__) && defined(__FMA__)
        const __m256 vb1 = _mm256_set1_ps(beta1);
        const __m256 vb2 = _mm256_set1_ps(beta2);
        const __m256 v1mb1 = _mm256_set1_ps(1.0f - beta1);
        const __m256 v1mb2 = _mm256_set1_ps(1.0f - beta2);
        const __m256 veps = _mm256_set1_ps(eps);
        const __m256 vstep = _mm256_set1_ps(step_size);
        const __m256 vbc2s = _mm256_set1_ps(bc2_sqrt);
        const __m256 vwd = _mm256_set1_ps(weight_decay);
        const __m256 vlwd = _mm256_set1_ps(1.0f - lr * weight_decay);
#pragma omp for
        for (int64_t i = 0; i <= n - 8; i += 8) {
            __m256 g = _mm256_loadu_ps(grads + i);
            __m256 p = _mm256_loadu_ps(params + i);
            if (weight_decay != 0.0f) {
                if (adamw) {
                    p = _mm256_mul_ps(p, vlwd);
                } else {
                    g = _mm256_fmadd_ps(vwd, p, g);
                }
            }
            __m256 m = _mm256_loadu_ps(exp_avg + i);
            __m256 v = _mm256_loadu_ps(exp_avg_sq + i);
            m = _mm256_fmadd_ps(vb1, m, _mm256_mul_ps(v1mb1, g));
            v = _mm256_fmadd_ps(vb2, v,
                                _mm256_mul_ps(v1mb2, _mm256_mul_ps(g, g)));
            __m256 denom = _mm256_fmadd_ps(_mm256_sqrt_ps(v),
                                           _mm256_set1_ps(1.0f / bc2_sqrt),
                                           veps);
            (void)vbc2s;
            p = _mm256_sub_ps(p, _mm256_div_ps(_mm256_mul_ps(vstep, m),
                                               denom));
            _mm256_storeu_ps(params + i, p);
            _mm256_storeu_ps(exp_avg + i, m);
            _mm256_storeu_ps(exp_avg_sq + i, v);
        }
        // scalar tail (single thread is fine: < 8 elements)
#pragma omp single
        for (int64_t i = n - (n % 8); i < n; ++i) {
            float g = grads[i];
            float p = params[i];
            if (weight_decay != 0.0f) {
                if (adamw) p *= 1.0f - lr * weight_decay;
                else g += weight_decay * p;
            }
            float m = exp_avg[i] = beta1 * exp_avg[i] + (1.0f - beta1) * g;
            float v = exp_avg_sq[i] =
                beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
            params[i] = p - step_size * m / (std::sqrt(v) / bc2_sqrt + eps);
        }
#else
#pragma omp for simd
        for (int64_t i = 0; i < n; ++i) {
            float g = grads[i];
            float p = params[i];
            if (weight_decay != 0.0f) {
                if (adamw) p *= 1.0f - lr * weight_decay;
                else g += weight_decay * p;
            }
            float m = exp_avg[i] = beta1 * exp_avg[i] + (1.0f - beta1) * g;
            float v = exp_avg_sq[i] =
                beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
            params[i] = p - step_size * m / (std::sqrt(v) / bc2_sqrt + eps);
        }
#endif
    }
}

// Adam step that also maintains a bf16 mirror of the params — the analogue
// of the reference's fp16 copy-back (cpu_adam.h dual-stream param copy):
// the bf16 buffer is what gets shipped to the TPU.
void ds_adam_step_bf16(float* params, uint16_t* params_bf16,
                       const float* grads, float* exp_avg, float* exp_avg_sq,
                       int64_t n, float lr, float beta1, float beta2,
                       float eps, float weight_decay, int adamw,
                       int64_t step) {
    ds_adam_step(params, grads, exp_avg, exp_avg_sq, n, lr, beta1, beta2,
                 eps, weight_decay, adamw, step);
#pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, params + i, 4);
        // round-to-nearest-even bf16 truncation
        uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
        params_bf16[i] = (uint16_t)((bits + rounding) >> 16);
    }
}

void ds_adagrad_step(float* params, const float* grads, float* exp_avg_sq,
                     int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        if (weight_decay != 0.0f) g += weight_decay * params[i];
        float v = exp_avg_sq[i] = exp_avg_sq[i] + g * g;
        params[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

}  // extern "C"
