// Async file I/O engine for ZeRO-Infinity-style NVMe offload.
//
// Reference analogue: csrc/aio/ — deepspeed_aio_handle_t
// (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp: thread pool, block_size /
// queue_depth / single_submit / overlap_events knobs, sync + async
// pread/pwrite + wait()). The reference uses libaio against O_DIRECT fds;
// this image has no libaio, so the engine is a portable POSIX thread pool
// issuing blocked pread/pwrite — same handle API and concurrency structure
// (requests split into block_size chunks spread over queue_depth workers),
// O_DIRECT attempted and transparently dropped where unsupported.
//
// C ABI (loaded via ctypes, see deepspeed_tpu/ops/op_builder.py):
//   aio_handle_new(block_size, queue_depth, num_threads) -> handle*
//   aio_handle_free(handle*)
//   aio_pread / aio_pwrite        — async, returns request id immediately
//   aio_sync_pread / aio_sync_pwrite — blocking, returns bytes or -errno
//   aio_wait(handle*)             — wait for ALL in-flight requests;
//                                   returns number completed, <0 on error

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Chunk {
    int fd;
    void* buf;
    int64_t nbytes;
    int64_t offset;
    bool write;
    std::atomic<int64_t>* remaining;   // per-request chunk counter
    std::atomic<int64_t>* errors;
};

struct Handle {
    int64_t block_size;
    int queue_depth;
    std::vector<std::thread> workers;
    std::deque<Chunk> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    int64_t inflight = 0;          // chunks queued or running
    bool stop = false;
    std::atomic<int64_t> total_errors{0};
    // per-request bookkeeping
    std::mutex req_mu;
    std::vector<std::pair<std::atomic<int64_t>*, std::atomic<int64_t>*>> reqs;

    void worker() {
        for (;;) {
            Chunk c;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv.wait(lk, [&] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                c = queue.front();
                queue.pop_front();
            }
            int64_t done = 0;
            while (done < c.nbytes) {
                ssize_t r = c.write
                    ? pwrite(c.fd, (char*)c.buf + done, c.nbytes - done,
                             c.offset + done)
                    : pread(c.fd, (char*)c.buf + done, c.nbytes - done,
                            c.offset + done);
                if (r < 0) { c.errors->fetch_add(1); total_errors++; break; }
                if (r == 0) break;  // EOF on read
                done += r;
            }
            c.remaining->fetch_sub(1);
            {
                std::lock_guard<std::mutex> lk(mu);
                if (--inflight == 0) done_cv.notify_all();
            }
        }
    }
};

int64_t submit(Handle* h, int fd, void* buf, int64_t nbytes, int64_t offset,
               bool write) {
    auto* remaining = new std::atomic<int64_t>(0);
    auto* errors = new std::atomic<int64_t>(0);
    int64_t nchunks = (nbytes + h->block_size - 1) / h->block_size;
    if (nchunks == 0) nchunks = 1;
    remaining->store(nchunks);
    {
        std::lock_guard<std::mutex> lk(h->req_mu);
        h->reqs.emplace_back(remaining, errors);
    }
    {
        std::lock_guard<std::mutex> lk(h->mu);
        for (int64_t i = 0; i < nchunks; ++i) {
            int64_t off = i * h->block_size;
            int64_t len = std::min(h->block_size, nbytes - off);
            if (len <= 0) len = 0;
            h->queue.push_back(Chunk{fd, (char*)buf + off, len,
                                     offset + off, write, remaining, errors});
            h->inflight++;
        }
    }
    h->cv.notify_all();
    return nchunks;
}

}  // namespace

extern "C" {

void* aio_handle_new(int64_t block_size, int queue_depth, int num_threads) {
    auto* h = new Handle();
    h->block_size = block_size > 0 ? block_size : (1 << 20);
    h->queue_depth = queue_depth > 0 ? queue_depth : 8;
    int nt = num_threads > 0 ? num_threads : h->queue_depth;
    for (int i = 0; i < nt; ++i)
        h->workers.emplace_back([h] { h->worker(); });
    return h;
}

void aio_handle_free(void* hp) {
    auto* h = (Handle*)hp;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->stop = true;
    }
    h->cv.notify_all();
    for (auto& t : h->workers) t.join();
    for (auto& pr : h->reqs) { delete pr.first; delete pr.second; }
    delete h;
}

int aio_open(const char* path, int for_write, int direct) {
    int flags = for_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
    if (direct) flags |= O_DIRECT;
#endif
    int fd = open(path, flags, 0644);
#ifdef O_DIRECT
    if (fd < 0 && direct)  // fs without O_DIRECT support: retry buffered
        fd = open(path, for_write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
#endif
    return fd;
}

void aio_close(int fd) { close(fd); }

int64_t aio_pread(void* hp, int fd, void* buf, int64_t nbytes,
                  int64_t offset) {
    return submit((Handle*)hp, fd, buf, nbytes, offset, false);
}

int64_t aio_pwrite(void* hp, int fd, void* buf, int64_t nbytes,
                   int64_t offset) {
    return submit((Handle*)hp, fd, buf, nbytes, offset, true);
}

int64_t aio_wait(void* hp) {
    auto* h = (Handle*)hp;
    std::unique_lock<std::mutex> lk(h->mu);
    h->done_cv.wait(lk, [&] { return h->inflight == 0; });
    int64_t errs = h->total_errors.exchange(0);
    {
        std::lock_guard<std::mutex> rlk(h->req_mu);
        for (auto& pr : h->reqs) { delete pr.first; delete pr.second; }
        h->reqs.clear();
    }
    return errs == 0 ? 0 : -errs;
}

int64_t aio_sync_pread(int fd, void* buf, int64_t nbytes, int64_t offset) {
    int64_t done = 0;
    while (done < nbytes) {
        ssize_t r = pread(fd, (char*)buf + done, nbytes - done, offset + done);
        if (r < 0) return -errno;
        if (r == 0) break;
        done += r;
    }
    return done;
}

int64_t aio_sync_pwrite(int fd, void* buf, int64_t nbytes, int64_t offset) {
    int64_t done = 0;
    while (done < nbytes) {
        ssize_t r = pwrite(fd, (char*)buf + done, nbytes - done,
                           offset + done);
        if (r < 0) return -errno;
        done += r;
    }
    return done;
}

}  // extern "C"
